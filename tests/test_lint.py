"""mv2tlint analyzer tests: each pass against its seeded fixture (exact
finding counts AND locations), a zero-findings clean fixture, the
baseline ratchet (suppression, stale-entry strictness), the inline
ignore escape, and the tier-1 gate itself — `mv2tlint --strict` over the
live repo must exit 0."""

import json
import os
import subprocess
import sys

import pytest

from mvapich2_tpu.analysis import core
from mvapich2_tpu.analysis.cli import main as lint_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "lint")

pytestmark = pytest.mark.lint


def _lint(name):
    mods, errs = core.scan_paths([os.path.join(FIXTURES, name)])
    assert not errs
    return core.run_passes(mods)


def _locs(findings, pass_id):
    return [(f.pass_id, f.line) for f in findings if f.pass_id == pass_id]


# -- one seeded fixture per pass: exact counts + locations ---------------

def test_locks_pass_fixture():
    fs = _lint("bad_locks.py")
    assert _locs(fs, "locks") == [("locks", 19)]
    assert len(fs) == 1
    (f,) = fs
    assert "'items'" in f.msg and "_lock" in f.msg and "Hot.bad" in f.msg


def test_tags_pass_fixture():
    fs = _lint("bad_tags.py")
    assert _locs(fs, "tags") == [("tags", 5), ("tags", 6)]
    assert len(fs) == 2
    assert "overlaps ALPHA_TAG_BASE" in fs[0].msg
    assert "dynamic next_coll_tag window" in fs[1].msg


def test_registry_pass_fixture():
    fs = _lint("bad_registry.py")
    assert _locs(fs, "pvars") == [("pvars", 11), ("pvars", 13),
                                  ("pvars", 17), ("pvars", 21),
                                  ("pvars", 25)]
    assert len(fs) == 5
    msgs = "\n".join(f.msg for f in fs)
    assert "badLower" in msgs and "Fixture_Bad" in msgs
    assert "fixture_never_declared" in msgs
    assert "MV2T_NOT_A_CVAR" in msgs and "UNDECLARED_KNOB" in msgs


def test_blocking_pass_fixture():
    fs = _lint("bad_blocking.py")
    assert _locs(fs, "blocking") == [("blocking", 12), ("blocking", 13),
                                     ("blocking", 17)]
    assert len(fs) == 3
    msgs = "\n".join(f.msg for f in fs)
    assert "time.sleep" in msgs and "acquire" in msgs and "wait" in msgs


def test_traceguard_pass_fixture():
    fs = _lint("bad_traceguard.py")
    assert _locs(fs, "traceguard") == [("traceguard", 8),
                                       ("traceguard", 11)]
    assert len(fs) == 2


def test_clean_fixture_zero_findings():
    assert _lint("clean.py") == []


# -- suppression machinery ----------------------------------------------

def test_inline_ignore_comment(tmp_path):
    src = ("class Chan:\n"
           "    def f(self, engine):\n"
           "        tr = engine.tracer\n"
           "        tr.record('mpi', 'y')  # mv2tlint: ignore[traceguard]\n")
    p = tmp_path / "ignored.py"
    p.write_text(src)
    mods, _ = core.scan_paths([str(p)])
    assert core.run_passes(mods) == []


def test_baseline_suppresses_and_ratchets(tmp_path):
    fixture = os.path.join(FIXTURES, "bad_locks.py")
    mods, _ = core.scan_paths([fixture])
    (f,) = core.run_passes(mods)
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"suppressions": [
        {"pass": f.pass_id, "path": f.path, "msg": f.msg, "reason": "t"}]}))
    # suppressed: exit 0 even under --strict
    assert lint_main([fixture, "--baseline", str(bl), "--strict"]) == 0
    # a STALE entry (nothing matches it) passes plain mode but fails
    # --strict: the invariant set only ratchets down
    bl.write_text(json.dumps({"suppressions": [
        {"pass": f.pass_id, "path": f.path, "msg": f.msg, "reason": "t"},
        {"pass": "tags", "path": "gone.py", "msg": "fixed long ago",
         "reason": "stale"}]}))
    assert lint_main([fixture, "--baseline", str(bl)]) == 0
    assert lint_main([fixture, "--baseline", str(bl), "--strict"]) == 1


def test_unsuppressed_finding_fails(tmp_path):
    fixture = os.path.join(FIXTURES, "bad_tags.py")
    assert lint_main([fixture, "--no-baseline"]) == 1


def test_write_baseline_roundtrip(tmp_path):
    fixture = os.path.join(FIXTURES, "bad_registry.py")
    bl = tmp_path / "bl.json"
    assert lint_main([fixture, "--baseline", str(bl),
                      "--write-baseline"]) == 0
    assert len(json.load(open(bl))["suppressions"]) == 5
    assert lint_main([fixture, "--baseline", str(bl), "--strict"]) == 0


def test_parse_error_is_a_finding(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    mods, errs = core.scan_paths([str(p)])
    assert not mods and len(errs) == 1 and errs[0].pass_id == "parse"


# -- the tier-1 gate: the live repo is clean under --strict --------------

def test_repo_strict_clean():
    """`mv2tlint --strict` over the package: no new findings, no stale
    baseline entries. THE ratchet — a regression in any of the five
    invariant families fails tier-1 here."""
    assert lint_main(["--strict"]) == 0


def test_bin_entrypoint_ci_invocation():
    """The CI-style command line from the issue, through bin/mv2tlint."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "mv2tlint"),
         "--baseline", "analysis/baseline.json", "--strict"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "0 finding(s)" in r.stdout


def test_list_passes():
    assert lint_main(["--list-passes"]) == 0


# -- the native pass: C-plane atomic discipline + layout -----------------

from mvapich2_tpu.analysis import native as native_mod  # noqa: E402


def _lint_native(name):
    return native_mod.NativeSourcePass(
        [os.path.join(FIXTURES, name)], layout=False).run([])


def test_native_pass_bad_fixture():
    """Seeded C fixture: exact finding count and locations, one per
    protocol family (doorbell plain store, volatile-only lease read,
    order-less __atomic, guarded-by without the lock, raw seqlock
    deref, rationale-less counter, seqlock pairing)."""
    fs = _lint_native("bad_native.c")
    assert [(f.pass_id, f.line) for f in fs] == [
        ("native", 0), ("native", 20), ("native", 26), ("native", 30),
        ("native", 34), ("native", 38), ("native", 57)]
    msgs = "\n".join(f.msg for f in fs)
    assert "doorbell" in msgs and "lease" in msgs
    assert "seqlock(wave)" in msgs and "guarded-by mu" in msgs
    assert "__ATOMIC_" in msgs and "rationale" in msgs
    assert "fanout" in msgs          # pairing: writer without reader


def test_native_pass_clean_fixture():
    assert _lint_native("clean_native.c") == []


def test_native_pass_repo_clean():
    """The committed native tree is clean: zero unbaselined findings
    from the native pass (including the layout cross-check)."""
    fs = native_mod.NativeSourcePass().run([])
    assert fs == [], [f.render() for f in fs]


def test_native_pass_catches_seed_violation_class(tmp_path):
    """Mutation check with teeth: re-introduce the exact class of bug
    fixed in this PR's seed run (plain store to the shared failure
    byte) and prove the pass catches it."""
    src = open(os.path.join(REPO, "native", "cplane.cpp")).read()
    mutated = src.replace(
        "__atomic_store_n(&p->failed[ring_index], 1, __ATOMIC_RELEASE);",
        "p->failed[ring_index] = 1;")
    assert mutated != src
    p = tmp_path / "cplane_mut.cpp"
    p.write_text(mutated)
    fs = native_mod.NativeSourcePass([str(p)], layout=False).run([])
    assert any("'failed' plainly accessed" in f.msg for f in fs), \
        [f.msg for f in fs]


def test_native_layout_mismatch_detected(tmp_path):
    """A drifted cross-language constant is a finding: doctor the
    header's ring-header size away from shm.py's _HEADER."""
    real = open(os.path.join(REPO, "native", "shm_layout.h")).read()
    hdr = tmp_path / "shm_layout.h"
    hdr.write_text(real.replace("#define MV2T_RING_HDR_BYTES 128",
                                "#define MV2T_RING_HDR_BYTES 64"))
    fs = native_mod.NativeSourcePass([], layout=True,
                                     layout_header=str(hdr)).run([])
    assert any("MV2T_RING_HDR_BYTES" in f.msg and "disagree" in f.msg
               for f in fs), [f.msg for f in fs]


def test_native_layout_fpc_drift_detected(tmp_path):
    """Renumbering a fast-path counter slot desyncs the FPC enum from
    shm.py's _FP_COUNTERS — mechanical finding, not convention."""
    real = open(os.path.join(REPO, "native", "shm_layout.h")).read()
    hdr = tmp_path / "shm_layout.h"
    hdr.write_text(real.replace("FPC_DEAD_PEER = 11",
                                "FPC_DEAD_PEER = 12"))
    fs = native_mod.NativeSourcePass([], layout=True,
                                     layout_header=str(hdr)).run([])
    assert any("FPC" in f.msg or "_FP_COUNTERS" in f.msg for f in fs), \
        [f.msg for f in fs]


def test_native_cli_routes_c_paths():
    """mv2tlint accepts C files on the command line and routes them to
    the native pass (fixture mode)."""
    assert lint_main([os.path.join(FIXTURES, "bad_native.c"),
                      "--no-baseline"]) == 1
    assert lint_main([os.path.join(FIXTURES, "clean_native.c"),
                      "--no-baseline"]) == 0


def test_native_pass_in_default_gate():
    """The tier-1 strict gate includes the native pass — a new
    unbaselined native finding fails tier-1 through
    test_repo_strict_clean above."""
    assert any(p.id == "native" for p in core.all_passes())


def test_runtests_tsan_lane_wired():
    """bin/runtests grew the --tsan lane; the Makefile has the variant
    targets and the vetted suppressions file exists."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "runtests"),
         "--help"], capture_output=True, text=True, timeout=60)
    assert "--tsan" in r.stdout and "--lint" in r.stdout
    mk = open(os.path.join(REPO, "native", "Makefile")).read()
    assert "fsanitize=thread" in mk and "tsan/libmpi.so" in mk
    assert os.path.exists(os.path.join(REPO, "native", "tsan.supp"))


def test_watchdog_shared_field_map():
    """The stall watchdog names protocol regions from the native
    pass's shared-field map (seqlock/lease/doorbell forensics)."""
    from mvapich2_tpu.trace import watchdog
    m = watchdog._field_map()
    assert m, "shared-field map is empty"
    assert m["fl_in"]["kind"] == "seqlock"
    assert m["fl_in"]["region"] == "flat"
    assert m["lease"]["kind"] == "atomic"
    assert m["flags"]["region"] == "doorbell"
    assert watchdog._region_tag(m, "lease") == " [atomic(lease)]"
    lines = watchdog._protocol_map_lines(m)
    assert any("seqlock(flat)" in ln for ln in lines)
    assert any("atomic(doorbell)" in ln for ln in lines)


def test_device_engine_under_lint_ratchet():
    """ISSUE 8 satellite: the HBM-streaming kernel modules ride the
    same passes as the host path — pallas_ici / _compat / pallas_ring
    are in the scanned set, their trace site follows the guarded idiom
    (coll/device.py dev_coll_fallback instant), and a seeded violation
    of each class in a device-engine-shaped module is caught."""
    import mvapich2_tpu
    from mvapich2_tpu.analysis import core as acore

    pkg = os.path.dirname(mvapich2_tpu.__file__)
    modules, errors = acore.scan_paths([pkg])
    assert not errors
    names = {os.path.relpath(m.path, pkg) for m in modules}
    for need in ("ops/pallas_ici.py", "ops/_compat.py",
                 "ops/pallas_ring.py", "bench/dev_sweep.py"):
        assert need in names, need
    # the committed device modules are clean under the pvars +
    # traceguard passes (no new baseline entries)
    from mvapich2_tpu.analysis.registry import RegistryPass
    from mvapich2_tpu.analysis.traceguard import TraceGuardPass
    dev = [m for m in modules
           if os.path.relpath(m.path, pkg).startswith(("ops/", "bench/"))
           or os.path.relpath(m.path, pkg) == "coll/device.py"]
    fs = RegistryPass().run(modules)   # pvar decls are cross-module
    dev_paths = {m.path for m in dev}
    assert [f for f in fs if f.path in dev_paths] == []
    assert [f for f in TraceGuardPass().run(dev)] == []
    # a seeded unguarded trace site + undeclared pvar in a kernel-shaped
    # module is caught (the ratchet actually bites)
    bad = acore.SourceModule("ops/bad_ici_fixture.py", (
        "from .. import mpit\n"
        "def hbm_ring(tracer):\n"
        "    mpit.pvar('dev_coll_never_declared').inc()\n"
        "    tracer.record('channel', 'x', 'i')\n"))
    assert len(RegistryPass().run(modules + [bad])) == 1
    assert len(TraceGuardPass().run([bad])) == 1


def test_startup_modules_under_lint_ratchet():
    """ISSUE 9 satellite: the startup-path modules (light boot, warm-
    attach daemon, cabi_boot, churn bench) ride the same passes as the
    datapath — they are in the scanned set, clean under the pvars and
    blocking passes, and a seeded violation of each class in a
    daemon-shaped module is caught (the ratchet actually bites)."""
    import mvapich2_tpu
    from mvapich2_tpu.analysis import core as acore

    pkg = os.path.dirname(mvapich2_tpu.__file__)
    modules, errors = acore.scan_paths([pkg])
    assert not errors
    names = {os.path.relpath(m.path, pkg) for m in modules}
    for need in ("runtime/boot.py", "runtime/daemon.py", "cabi_boot.py",
                 "bench/churn.py"):
        assert need in names, need
    from mvapich2_tpu.analysis.blocking import BlockingCallPass
    from mvapich2_tpu.analysis.registry import RegistryPass
    start_paths = {m.path for m in modules
                   if os.path.relpath(m.path, pkg) in
                   ("runtime/boot.py", "runtime/daemon.py",
                    "cabi_boot.py", "bench/churn.py",
                    "transport/shm.py")}
    fs = RegistryPass().run(modules)   # cvar/pvar decls are cross-module
    assert [f for f in fs if f.path in start_paths] == []
    assert [f for f in BlockingCallPass().run(
        [m for m in modules if m.path in start_paths])
        if f.path in start_paths] == []
    # a seeded undeclared-cvar env read + undeclared pvar in a
    # daemon-shaped module is caught
    bad = acore.SourceModule("runtime/bad_daemon_fixture.py", (
        "import os\n"
        "from .. import mpit\n"
        "def claim():\n"
        "    os.environ.get('MV2T_DAEMON_NEVER_DECLARED')\n"
        "    mpit.pvar('daemon_claims_never_declared').inc()\n"))
    assert len(RegistryPass().run(modules + [bad])) == 2


# -- traceguard native half (MV2T_NTRACE gate discipline) ----------------

def test_traceguard_ntrace_fixture():
    """ISSUE 10 satellite: seeded native fixture — raw nt_emit calls
    (one inline-guarded, guards don't substitute for the macro) and a
    gateless MV2T_NTRACE macro definition; the inline-ignored line is
    suppressed. Exact count + locations."""
    from mvapich2_tpu.analysis.traceguard import TraceGuardPass
    p = os.path.join(FIXTURES, "bad_ntrace.c")
    fs = TraceGuardPass(native_sources=[p]).run([])
    assert sorted(_locs(fs, "traceguard")) == [
        ("traceguard", 7),    # gateless macro definition
        ("traceguard", 12),   # raw call on the send path
        ("traceguard", 16),   # raw call behind an inline guard (the
                              # statement spans lines 16-17)
    ]
    assert len(fs) == 3
    msgs = "\n".join(f.msg for f in fs)
    assert "MV2T_NTRACE" in msgs and "nt_emit" in msgs


def test_traceguard_ntrace_committed_tree_clean():
    """The committed native tree satisfies the gate discipline (every
    emit rides the macro; both macro definitions carry the gate or the
    ((void)0) stub)."""
    from mvapich2_tpu.analysis.traceguard import TraceGuardPass
    assert TraceGuardPass().run([]) == []


def test_traceguard_ntrace_mutation_caught(tmp_path):
    """Re-introduce the bug class: copy cplane.cpp's emit pattern with
    the macro bypassed — the pass flags it."""
    from mvapich2_tpu.analysis.traceguard import TraceGuardPass
    p = tmp_path / "mutated.c"
    p.write_text(
        "void nt_emit(void* p, int ev, long a1, long a2);\n"
        "static void ring_bell(void* p, int dst) {\n"
        "  nt_emit(p, 4, dst, 0);\n"
        "}\n")
    fs = TraceGuardPass(native_sources=[str(p)]).run([])
    assert len(fs) == 1 and fs[0].line == 3


# -- the device pass: Pallas DMA/semaphore discipline (ISSUE 12) ---------

def test_device_pass_fixture():
    """Seeded device fixture: exact finding count and locations, one
    per invariant family (dead pending map, early-exit unawaited copy,
    unbound copy, park-without-drain, half-drained remote park,
    unannotated creditless gate, gateless credit op, signal-only
    semaphore, VMEM budget blow)."""
    fs = _lint("bad_device.py")
    assert _locs(fs, "device") == [
        ("device", 17),   # dead pending_ghost map
        ("device", 23),   # early-exit return past started 'ld'
        ("device", 28),   # unbound make_async_copy
        ("device", 35),   # pending_acc parked, never drained
        ("device", 42),   # pending_send drains wait_send only
        ("device", 49),   # gate present but not '# device: hw-only'
        ("device", 58),   # done_sem op has no creditless gate
        ("device", 58),   # done_sem signaled, never waited
        ("device", 64),   # 256 MiB VMEM scratch > tier cap
    ]
    assert len(fs) == 9
    msgs = "\n".join(f.msg for f in fs)
    assert "pending_ghost" in msgs and "early_exit" in msgs
    assert "wait_recv" in msgs and "hw-only" in msgs
    assert "done_sem" in msgs and "VMEM scratch budget" in msgs


def test_clean_device_fixture_zero_findings():
    assert _lint("clean_device.py") == []


def test_device_pass_in_default_gate():
    """The tier-1 strict gate includes the device and profile passes —
    a new unbaselined finding fails tier-1 through
    test_repo_strict_clean."""
    ids = {p.id for p in core.all_passes()}
    assert {"device", "profile"} <= ids


def test_device_pass_committed_kernels_clean():
    """The committed kernel modules are clean under the device pass —
    every genuine finding of the seed run (dead pending_in map,
    unannotated creditless gates) is FIXED, not baselined."""
    from mvapich2_tpu.analysis.device import DevicePass
    mods, errs = core.scan_paths([os.path.join(REPO, "mvapich2_tpu")])
    assert not errs
    assert DevicePass().run(mods) == []


def test_device_pass_catches_seed_violation_classes(tmp_path):
    """Mutation check with teeth: re-introduce the exact classes fixed
    in this PR's seed run and prove the pass catches each one."""
    from mvapich2_tpu.analysis.device import DevicePass
    src = open(os.path.join(REPO, "mvapich2_tpu", "ops",
                            "pallas_ici.py")).read()
    # (a) the dead pending map that shipped with PR 8
    mut = src.replace(
        "self.pending_send: Dict = {}           # (d, slot) -> remote handle",
        "self.pending_send: Dict = {}           # (d, slot) -> remote handle\n"
        "        self.pending_in: Dict = {}")
    assert mut != src
    # (b) strip one hw-only annotation from a creditless gate
    mut = mut.replace("def _grant(self, d):                      "
                      "# device: hw-only",
                      "def _grant(self, d):")
    p = tmp_path / "pallas_ici_mut.py"
    p.write_text(mut)
    mods, errs = core.scan_paths([str(p)])
    assert not errs
    fs = DevicePass(profiles=[]).run(mods)
    msgs = "\n".join(f.msg for f in fs)
    assert "pending_in" in msgs, msgs
    assert "not annotated '# device: hw-only'" in msgs, msgs
    # (c) delete a wait: the handle leaks out of the kernel
    mut2 = src.replace("        ld.wait()\n", "")
    assert mut2 != src
    p2 = tmp_path / "pallas_ici_mut2.py"
    p2.write_text(mut2)
    mods2, _ = core.scan_paths([str(p2)])
    fs2 = DevicePass(profiles=[]).run(mods2)
    assert any("'ld'" in f.msg and "without a matching wait" in f.msg
               for f in fs2), [f.msg for f in fs2]


def test_device_vmem_budget_rejects_bad_profile(tmp_path):
    """A committed chunk-size/depth combination that cannot fit in VMEM
    is a lint failure, not a Mosaic OOM on the TPU host: a profile
    claiming ici_chunk_bytes=4 MiB blows the scratch budget of the
    committed streaming kernel (3 buffers x 2 dirs x depth 2)."""
    import json as _json

    from mvapich2_tpu.analysis.device import DevicePass
    prof = tmp_path / "cpu_cpu_8.json"
    prof.write_text(_json.dumps({
        "arch_key": "cpu:cpu:8", "format": "mv2t-tuning-profile-v1",
        "profile": {"kernel_params": {"ici_chunk_bytes": 4 << 20}}}))
    mods, _ = core.scan_paths([os.path.join(REPO, "mvapich2_tpu", "ops",
                                            "pallas_ici.py"),
                               os.path.join(REPO, "mvapich2_tpu",
                                            "mpit.py")])
    fs = DevicePass(profiles=[str(prof)]).run(mods)
    assert any("VMEM scratch budget" in f.msg and "cpu_cpu_8.json" in f.msg
               for f in fs), [f.msg for f in fs]
    # the committed profiles fit
    assert DevicePass().run(mods) == []


def test_device_lane_map():
    """The lane map the watchdog/mpistat device sections read: the
    committed streaming engine's pending containers with their drain
    kinds, and the paired credit semaphore."""
    from mvapich2_tpu.analysis.device import device_lane_map
    m = device_lane_map(refresh=True)
    assert m["pending_send"]["kind"] == "pending-map"
    assert m["pending_send"]["remote"] is True
    assert {"wait_send", "wait_recv"} <= set(m["pending_send"]["drains"])
    assert m["pending_store"]["drains"] == ["wait"]
    assert m["cap_sem"]["kind"] == "credit-sem"
    assert m["cap_sem"]["signals"] >= 1 and m["cap_sem"]["waits"] >= 1


def test_watchdog_device_map_lines():
    """PR 7 parity (shared_field_map region tagging): the stall report
    and mpistat share one device-lane protocol map section."""
    from mvapich2_tpu.trace import watchdog
    lines = watchdog.device_map_lines()
    text = "\n".join(lines)
    assert "device-lane protocol map" in text
    assert "pending-map pending_send [remote]" in text
    assert "credit-sem cap_sem" in text


def test_mpistat_device_map_flag(capsys):
    from mvapich2_tpu.trace.mpistat import main as mpistat_main
    assert mpistat_main(["--device-map"]) == 0
    out = capsys.readouterr().out
    assert "pending_send" in out and "cap_sem" in out


# -- the one-sided engine under the device pass (ISSUE 16) ---------------

def test_device_pass_catches_rma_seed_violation_classes(tmp_path):
    """Mutation check with teeth for ops/pallas_rma.py: re-introduce
    the violation classes the device pass guards the one-sided engine
    against — a dead pending map, an unannotated creditless gate, and
    a started fold-operand load whose handle leaks out of the kernel —
    and prove the pass catches each one."""
    from mvapich2_tpu.analysis.device import DevicePass
    src = open(os.path.join(REPO, "mvapich2_tpu", "ops",
                            "pallas_rma.py")).read()
    # (a) a pending map that is never filled or drained
    mut = src.replace(
        "self.pending_store: Dict = {}          # slot -> commit store",
        "self.pending_store: Dict = {}          # slot -> commit store\n"
        "        self.pending_ack: Dict = {}")
    assert mut != src
    # (b) strip the hw-only annotation from the credit re-grant gate
    mut = mut.replace("def _grant(self):                         "
                      "# device: hw-only",
                      "def _grant(self):")
    p = tmp_path / "pallas_rma_mut.py"
    p.write_text(mut)
    mods, errs = core.scan_paths([str(p)])
    assert not errs
    fs = DevicePass(profiles=[]).run(mods)
    msgs = "\n".join(f.msg for f in fs)
    assert "pending_ack" in msgs, msgs
    assert "not annotated '# device: hw-only'" in msgs, msgs
    # (c) drop the park: the started window-operand load leaks out of
    # the accumulate kernel with no wait on any path
    mut2 = src.replace("                st.pending_fold[slot] = ld\n", "")
    assert mut2 != src
    p2 = tmp_path / "pallas_rma_mut2.py"
    p2.write_text(mut2)
    mods2, _ = core.scan_paths([str(p2)])
    fs2 = DevicePass(profiles=[]).run(mods2)
    assert any("'ld'" in f.msg and "without a matching wait" in f.msg
               for f in fs2), [f.msg for f in fs2]


def test_device_lane_map_covers_rma_containers():
    """The lane map the watchdog/mpistat device sections read grows the
    one-sided engine's containers: the fold-operand prefetch map (local,
    drained by wait) rides next to the remote send map."""
    from mvapich2_tpu.analysis.device import device_lane_map
    m = device_lane_map(refresh=True)
    assert m["pending_fold"]["kind"] == "pending-map"
    assert m["pending_fold"]["remote"] is False
    assert m["pending_fold"]["drains"] == ["wait"]
    assert m["pending_fold"]["module"].endswith("pallas_rma.py")


def test_watchdog_device_report_one_sided_counters():
    """The stall report's device section prints the dev_rma_* counter
    line once any one-sided op has run."""
    from types import SimpleNamespace

    from mvapich2_tpu import mpit
    from mvapich2_tpu.trace import watchdog
    mpit.pvar("dev_rma_tier_epoch").inc()
    mpit.pvar("dev_rma_flush").inc()
    ch = SimpleNamespace(rank=0, size=1, rv=None)
    u = SimpleNamespace(comm_world=SimpleNamespace(device_channel=ch))
    text = "\n".join(watchdog._device_report(u))
    assert "one-sided counters:" in text
    assert "dev_rma_tier_epoch" in text and "dev_rma_flush" in text


def test_rma_win_acc_mutex_bounded_and_baseline_empty():
    """The retired r4 baseline entry stays retired: the accumulate
    mutex acquires in rma/win.py are timeout-bounded (the blocking pass
    finds nothing), the locks baseline carries zero suppressions, and
    re-introducing the unbounded acquire is caught again."""
    win = os.path.join(REPO, "mvapich2_tpu", "rma", "win.py")
    mods, errs = core.scan_paths([win])
    assert not errs
    assert [f for f in core.run_passes(mods)
            if f.pass_id in ("blocking", "locks")] == []
    bl = core.load_baseline()
    assert bl.entries == [], bl.entries


def test_rma_win_unbounded_acquire_caught_again(tmp_path):
    """Strip the timeout bound from the _on_cas mutex acquire: the
    blocking pass must flag it — the empty baseline means the finding
    cannot come back silently."""
    src = open(os.path.join(REPO, "mvapich2_tpu", "rma",
                            "win.py")).read()
    mut = src.replace("cma.acquire(timeout=_ACC_MUTEX_TIMEOUT)",
                      "cma.acquire()")
    assert mut != src
    p = tmp_path / "win_mut.py"
    p.write_text(mut)
    mods, _ = core.scan_paths([str(p)])
    fs = [f for f in core.run_passes(mods) if f.pass_id == "blocking"]
    assert fs and any("acquire" in f.msg for f in fs), \
        [f.msg for f in core.run_passes(mods)]


# -- the profile doctor (ISSUE 12 tentpole piece 3) ----------------------

def test_profile_doctor_bad_fixture():
    """Seeded profile JSON: every schema violation class caught —
    unknown keys, filename/arch mismatch, unknown collective/class,
    unregistered algo, non-monotone and non-total bins, unknown
    symbolic edge, bad crossover keys/values, vmem edge past the hard
    wrapper cap, a quant edge below the vmem->hbm edge (ISSUE 15),
    typo'd/invalid kernel params."""
    from mvapich2_tpu.analysis.profilecheck import ProfileDoctorPass
    mods, _ = core.scan_paths([os.path.join(REPO, "mvapich2_tpu")])
    fs = ProfileDoctorPass(
        profile_files=[os.path.join(FIXTURES, "bad_profile.json")]
    ).run(mods)
    msgs = "\n".join(f.msg for f in fs)
    assert len(fs) == 16, msgs
    for needle in ("surprise", "tpu_TPU-v9_8.json", "mystery_section",
                   "non-final open (None) bin", "table not total",
                   "galactic", "warp_speed", "totally_real_algo",
                   "not strictly increasing", "frobnicate",
                   "dev_tier_quux", "not a byte count",
                   "VMEM wrapper cap", "quantized bin would swallow",
                   "ici_chunk_bites", "not a positive integer"):
        assert needle in msgs, needle


def test_profile_doctor_committed_profiles_clean():
    """Every committed arch profile matches the v1 schema — the gate
    the first REAL TPU profile commit (ROADMAP item 1) must pass."""
    from mvapich2_tpu.analysis.profilecheck import ProfileDoctorPass
    mods, _ = core.scan_paths([os.path.join(REPO, "mvapich2_tpu")])
    fs = ProfileDoctorPass().run(mods)
    assert fs == [], [f.render() for f in fs]


def test_profile_doctor_catches_default_table_drift(tmp_path):
    """Mutation: drift a DEFAULT_TABLES edge past its neighbor (the r5
    cliff shape) in a copy of tuning.py — the doctor flags it."""
    from mvapich2_tpu.analysis.profilecheck import ProfileDoctorPass
    src = open(os.path.join(REPO, "mvapich2_tpu", "coll",
                            "tuning.py")).read()
    mut = src.replace('"small": [(16 * 1024, "rd"), ("eager", "ring"),',
                      '"small": [(64 * 1024, "rd"), ("eager", "ring"),')
    assert mut != src
    d = tmp_path / "coll"
    d.mkdir()
    (d / "tuning.py").write_text(mut)
    mods, _ = core.scan_paths([str(d / "tuning.py")])
    fs = ProfileDoctorPass(profile_files=[]).run(mods)
    assert any("not strictly increasing" in f.msg for f in fs), \
        [f.msg for f in fs]
    # and a renamed symbolic edge leaves a dangling alias behind
    mut2 = src.replace('("eager", "ring")', '("eagre", "ring")')
    (d / "tuning.py").write_text(mut2)
    mods2, _ = core.scan_paths([str(d / "tuning.py")])
    fs2 = ProfileDoctorPass(profile_files=[]).run(mods2)
    assert any("unknown symbolic edge 'eagre'" in f.msg for f in fs2), \
        [f.msg for f in fs2]


def test_profile_doctor_cli_routes_json_paths():
    """mv2tlint accepts profile JSONs on the command line and routes
    them to the profile doctor — the 'validate before you commit a new
    arch profile' workflow from the README."""
    assert lint_main([os.path.join(FIXTURES, "bad_profile.json"),
                      "--no-baseline"]) == 1
    committed = os.path.join(REPO, "mvapich2_tpu", "profiles",
                             "cpu_cpu_8.json")
    assert lint_main([committed, "--no-baseline"]) == 0


# -- the cvar/env drift doctor (ISSUE 12 satellite) ----------------------

def test_env_drift_doctor_catches_undeclared_surfaces(tmp_path):
    """Seeded non-python surfaces: a native getenv, a bin script token
    and a README mention of MV2T_ names with no declared cvar are all
    findings; declared/internal names are not."""
    from mvapich2_tpu.analysis.registry import RegistryPass
    c = tmp_path / "rogue.c"
    c.write_text('static int dbg() { return getenv("MV2T_ROGUE_KNOB") '
                 '!= 0; }\n/* MV2T_NOT_A_GETENV_SO_NOT_SCANNED */\n')
    sh = tmp_path / "rogue_script"
    sh.write_text("#!/bin/sh\n: ${MV2T_ROGUE_SCRIPT_KNOB:=1}\n"
                  "echo $MV2T_RANK $MV2T_CC\n")       # internal: exempt
    md = tmp_path / "README.md"
    md.write_text("Set MV2T_ROGUE_DOC_KNOB=1 to win. MV2T_PEER_TIMEOUT "
                  "and MV2T_ALLREDUCE_ALGO are fine.\n")
    mods, _ = core.scan_paths([os.path.join(REPO, "mvapich2_tpu")])
    fs = RegistryPass(doc_sources=[str(c), str(sh), str(md)]).run(mods)
    drift = [f for f in fs if "ROGUE" in f.msg]
    assert len(drift) == 3, [f.msg for f in fs]
    assert not any("MV2T_CC" in f.msg or "MV2T_RANK" in f.msg
                   or "PEER_TIMEOUT" in f.msg
                   or "ALLREDUCE_ALGO" in f.msg for f in fs)


def test_env_drift_doctor_committed_surfaces_clean():
    """native getenv reads, bin/ scripts and the README all resolve
    against the registry — the three genuine seed findings
    (MV2T_CPLANE_DEBUG, MV2T_BENCH_INIT_BUDGET_MS, MV2T_DEVICE_WIN)
    are fixed by declaration, not exempted."""
    from mvapich2_tpu.analysis.registry import RegistryPass
    mods, _ = core.scan_paths([os.path.join(REPO, "mvapich2_tpu")])
    fs = [f for f in RegistryPass().run(mods)
          if "getenv" in f.msg or "mention" in f.msg]
    assert fs == [], [f.render() for f in fs]
    # the fixes are declarations (enumerable via mpiname/MPI_T), not
    # INTERNAL_ENV exemptions
    from mvapich2_tpu.analysis.registry import INTERNAL_ENV
    for env in ("MV2T_CPLANE_DEBUG", "MV2T_BENCH_INIT_BUDGET_MS",
                "MV2T_DEVICE_WIN"):
        assert env not in INTERNAL_ENV


def test_runtests_modelcheck_lane_wired():
    """bin/runtests grew the --modelcheck lane (the exhaustive
    long-horizon model configs) next to --lint/--tsan/--chaos."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "runtests"),
         "--help"], capture_output=True, text=True, timeout=60)
    assert "--modelcheck" in r.stdout


# -- the proto pass: control-plane verification (ISSUE 13) ---------------

_PKG_MODULES_CACHE = []


def _pkg_modules():
    # parsed once per session: SourceModules are read-only for passes,
    # and the ~130-file parse would otherwise repeat per mutation test
    if not _PKG_MODULES_CACHE:
        from mvapich2_tpu.analysis import core as acore
        mods, errs = acore.scan_paths(
            [os.path.join(REPO, "mvapich2_tpu")])
        assert not errs
        _PKG_MODULES_CACHE.append(mods)
    return list(_PKG_MODULES_CACHE[0])


def _mutated_pkg_modules(rel_suffix, transform):
    """The whole-package module set with ONE module's source mutated —
    the reintroduce-the-class harness (key flow is cross-module, so
    the mutation must be judged against the full tree)."""
    from mvapich2_tpu.analysis import core as acore
    out = []
    hit = False
    for m in _pkg_modules():
        if m.relpath.endswith(rel_suffix):
            src = transform(m.text)
            assert src != m.text, f"mutation did not apply to {rel_suffix}"
            out.append(acore.SourceModule(m.path, src))
            hit = True
        else:
            out.append(m)
    assert hit, rel_suffix
    return out


def test_proto_pass_fixture():
    """Seeded control-plane fixture: exact finding count and
    locations, one per invariant class — write-only key, drift pair
    (subsuming its orphans), never-written key, unbounded KVS retry
    loop, non-total wire state, version-skew consumer."""
    fs = _lint("bad_proto.py")
    assert _locs(fs, "proto") == [
        ("proto", 9),    # fixture-orphan-<*> written, never read
        ("proto", 11),   # boot-card-<*> vs boot_card-<*> drift
        ("proto", 16),   # fixture-ghost-<*> read, never written
        ("proto", 26),   # peek_many retry loop without a deadline
        ("proto", 42),   # wire stage 2 entered, never handled
        ("proto", 46),   # FIXTURE_MANIFEST_VERSION skew (no v2 handler)
    ]
    assert len(fs) == 6
    msgs = "\n".join(f.msg for f in fs)
    assert "fixture-orphan-<*>" in msgs and "never read" in msgs
    assert "boot-card-<*> vs boot_card-<*>" in msgs
    assert "fixture-ghost-<*>" in msgs and "blocks forever" in msgs
    assert "unbounded KVS wait" in msgs and "bounded-by" in msgs
    assert "not total" in msgs
    assert "fixture_manifest-v2" in msgs


def test_clean_proto_fixture_zero_findings():
    assert _lint("clean_proto.py") == []


def test_proto_pass_in_default_gate():
    """The tier-1 strict gate runs 10 passes including proto and the
    event-coverage doctor — a new unbaselined control-plane finding
    fails tier-1 through test_repo_strict_clean."""
    ids = [p.id for p in core.all_passes()]
    assert "proto" in ids and "events" in ids and len(ids) == 10


def test_proto_baseline_ratchet_stays_empty():
    """Strict mode for the new pass: the committed baseline carries NO
    proto entries — every genuine finding was fixed by change, and new
    ones cannot be baselined away silently."""
    bl = core.load_baseline()
    assert [e for e in bl.entries if e.get("pass") == "proto"] == []


# -- pass: events (trace event-coverage doctor) --------------------------

def test_events_pass_fixture():
    """Three seeded record sites outside the conformance grammar: a
    literal name, an f-string prefix (mystery_*), and a wrapper whose
    name parameter resolves through its call sites (the _trace_rma
    idiom). The covered literals / prefixes / wildcard-mpi sites stay
    silent, so the counts are exact."""
    fs = _lint("bad_events.py")
    assert _locs(fs, "events") == [("events", 10), ("events", 16),
                                   ("events", 18)]
    assert len(fs) == 3
    msgs = "\n".join(f.msg for f in fs)
    assert "bogus_wait" in msgs and "bogus_pulse" in msgs
    assert "mystery_*" in msgs


def test_events_pass_hist_and_nte_checks():
    """The _MET_HISTS / _NT_EVENTS halves key on trace/native.py being
    among the scanned modules: with the real one alongside the fixture,
    the unknown latency-sample name is a finding, the known one is
    silent, and the repo's own NTE->region map is fully covered by the
    cplane conformance grammar (zero NTE findings)."""
    from mvapich2_tpu.analysis.events import EventCoveragePass
    native = os.path.join(REPO, "mvapich2_tpu", "trace", "native.py")
    mods, errs = core.scan_paths(
        [os.path.join(FIXTURES, "bad_events.py"), native])
    assert not errs
    fs = EventCoveragePass().run(mods)
    assert [(f.line, "lat_bogus_thing" in f.msg) for f in fs
            if "_MET_HISTS" in f.msg] == [(27, True)]
    assert not any("NTE event" in f.msg for f in fs)


def test_events_grammar_exports():
    """The doctor consumes conform.event_grammars()/grammar_covers —
    the same tables the runtime checker matches against, so the static
    and dynamic views cannot drift apart."""
    from mvapich2_tpu.analysis import conform
    grams = conform.event_grammars()
    for layer in ("mpi", "protocol", "channel", "progress", "nbc",
                  "device", "cplane", "metrics"):
        assert layer in grams, layer
    assert conform.grammar_covers("device", "rma_lock")
    assert conform.grammar_covers("nbc", "sched_start")
    assert not conform.grammar_covers("device", "bogus_pulse")
    assert not conform.grammar_covers("nolayer", "anything")


def test_proto_pass_committed_tree_clean():
    """The committed control plane is clean under the proto pass —
    every genuine seed finding (write-only __agent_up_/__agent_exit_
    keys, timeout-less failure-watcher loops, unannotated wire states,
    the missing manifest-v1 handler annotation) is FIXED, not
    baselined."""
    from mvapich2_tpu.analysis.proto import ProtoPass
    assert ProtoPass().run(_pkg_modules()) == []


def test_proto_catches_agent_key_orphan_mutation():
    """Reintroduce the seed class: drop launch_tree's agent-protocol
    consumption and the __agent_up_/__agent_exit_ families go
    write-only again."""
    from mvapich2_tpu.analysis.proto import ProtoPass
    mods = _mutated_pkg_modules(
        "runtime/launcher.py",
        lambda s: s.replace('srv.peek(f"__agent_up_{node}")', "None")
                   .replace('srv.peek(f"__agent_exit_{node}")', "None"))
    fs = ProtoPass().run(mods)
    msgs = "\n".join(f.msg for f in fs)
    assert "'__agent_up_<*>' is written" in msgs, msgs
    assert "'__agent_exit_<*>' is written" in msgs


def test_proto_catches_key_family_drift_mutation():
    """THE motivating class: drift the verdict card's spelling
    (shm-cabi- -> shm_cabi-) on the write side only — the pass names
    both spellings instead of letting np=4 hang silently."""
    from mvapich2_tpu.analysis.proto import ProtoPass
    mods = _mutated_pkg_modules(
        "transport/shm.py",
        lambda s: s.replace('f"shm-cabi-{self.my_rank}": "1" if my_cabi',
                            'f"shm_cabi-{self.my_rank}": "1" if my_cabi'))
    fs = ProtoPass().run(mods)
    assert any("drift" in f.msg and "shm-cabi-<*>" in f.msg
               and "shm_cabi-<*>" in f.msg for f in fs), \
        [f.msg for f in fs]


def test_proto_catches_unbounded_watcher_mutation():
    """Strip the failure watcher's bounded-by annotation: the
    timeout-less retry loop is a finding again."""
    from mvapich2_tpu.analysis.proto import ProtoPass
    mods = _mutated_pkg_modules(
        "runtime/boot.py",
        lambda s: s.replace(
            "# proto: bounded-by(kvs-connection-lifetime)", "", 1))
    fs = ProtoPass().run(mods)
    assert any("unbounded KVS wait" in f.msg
               and f.path.endswith("runtime/boot.py") for f in fs), \
        [f.render() for f in fs]


def test_proto_catches_wire_state_mutations():
    """Strip a wire-state annotation AND add an unreachable stage:
    both the annotation discipline and totality bite."""
    from mvapich2_tpu.analysis.proto import ProtoPass
    mods = _mutated_pkg_modules(
        "transport/shm.py",
        lambda s: s.replace("if self._wire_stage == 1:   # state: wire:1",
                            "if self._wire_stage == 1:")
                   .replace("self._wire_stage = 1\n",
                            "self._wire_stage = 3\n"))
    fs = ProtoPass().run(mods)
    msgs = "\n".join(f.msg for f in fs)
    assert "'# state: wire:1' annotation" in msgs, msgs
    assert "wire state 3 is entered" in msgs


def test_proto_catches_manifest_version_mutations():
    """Bump MANIFEST_VERSION without a v3 handler annotation, and
    strip the existing v1/v2 ones — all are version-skew findings."""
    from mvapich2_tpu.analysis.proto import ProtoPass
    mods = _mutated_pkg_modules(
        "runtime/daemon.py",
        lambda s: s.replace("MANIFEST_VERSION = 3", "MANIFEST_VERSION = 4"))
    fs = ProtoPass().run(mods)
    assert any("manifest-v3" in f.msg for f in fs), [f.msg for f in fs]
    for stripped in ("# proto: manifest-v1", "# proto: manifest-v2"):
        mods = _mutated_pkg_modules(
            "runtime/daemon.py",
            lambda s, stripped=stripped: s.replace(stripped, ""))
        fs = ProtoPass().run(mods)
        want = stripped.split()[-1]
        assert any(want in f.msg for f in fs), [f.msg for f in fs]


def test_proto_state_map():
    """The exported control-plane map (shared_field_map /
    device_lane_map analog): key families with write/read sites, the
    annotated wire states, the version constants."""
    from mvapich2_tpu.analysis.proto import proto_state_map
    m = proto_state_map(refresh=True)
    keys = m["keys"]
    assert keys["shm-cabi-<*>"]["writes"] >= 2
    assert keys["shm-cabi-<*>"]["reads"] >= 1
    assert keys["__failure_ev_<*>"]["writes"] >= 2
    assert keys["tcp-addr-<*>"]["reads"] == 1
    assert set(m["wire_states"]) == {0, 1}
    assert all(v["annotated"] for v in m["wire_states"].values())
    assert m["versions"]["MANIFEST_VERSION"] >= 2
    assert m["versions"]["BOOT_PROTO_VERSION"] >= 1
    assert m["waits"] > 10


def test_watchdog_proto_map_lines():
    """PR 7/12 parity: the stall report and mpistat share one
    control-plane protocol map section."""
    from mvapich2_tpu.trace import watchdog
    lines = watchdog.proto_map_lines()
    text = "\n".join(lines)
    assert "control-plane protocol map" in text
    assert "wire states: 0 @" in text
    assert "MANIFEST_VERSION" in text
    assert "shm-cabi-<*>" in text


def test_watchdog_control_report_section():
    """The live half: per-peer wiring stage + bells + the in-flight
    wire deadline, from a channel-shaped object."""
    from mvapich2_tpu.trace import watchdog

    class FakeChan:
        my_rank = 0
        local_ranks = [0, 1, 2]
        cabi_ranks = {2}
        _wired = False
        _wire_stage = 1
        _peer_bells = {1: "/x"}
        _wire_deadline = 0.0
    import time as _t
    ch = FakeChan()
    ch._wire_deadline = _t.monotonic() + 42.0
    lines = watchdog._control_report(ch)
    text = "\n".join(lines)
    assert "wired=False, wire stage=1" in text
    assert "peer 1: bell set" in text
    assert "peer 2: bell UNSET [C-ABI]" in text
    assert "wire gate, deadline in" in text


def test_mpistat_proto_map_flag(capsys):
    from mvapich2_tpu.trace.mpistat import main as mpistat_main
    assert mpistat_main(["--proto-map"]) == 0
    out = capsys.readouterr().out
    assert "wire states" in out and "shm-cabi-<*>" in out


def test_mpistat_daemon_lines(tmp_path):
    """The daemon claim-cycle section reads one manifest.json — claim
    state, epoch, owner, version."""
    import json as _json

    from mvapich2_tpu.trace.mpistat import daemon_lines
    (tmp_path / "manifest.json").write_text(_json.dumps({
        "version": 2, "daemon_pid": 0,
        "sets": {"n2-r4194304-p268435456": {
            "state": "busy", "epoch": 7, "owner_pid": 12345}}}))
    lines = daemon_lines(str(tmp_path))
    text = "\n".join(lines)
    assert "manifest v2" in text
    assert "n2-r4194304-p268435456: busy epoch=7 owner=12345" in text
    assert daemon_lines(str(tmp_path / "nonexistent")) == []
    # the multi-tenant (v3) rows: occupancy vs quota, queue depth,
    # exec-cache size
    (tmp_path / "manifest.json").write_text(_json.dumps({
        "version": 3, "daemon_pid": 0, "exec_epoch": 2, "qseq": 3,
        "queue": [{"pid": 999, "geokey": "n2-x", "seq": 3}],
        "sets": {"n2-r4194304-p268435456-i0": {
            "geokey": "n2-r4194304-p268435456",
            "state": "busy", "epoch": 7, "owner_pid": 12345}}}))
    text = "\n".join(daemon_lines(str(tmp_path)))
    assert "occupancy: 1 busy / 1 provisioned" in text
    assert "queue depth 1" in text
    assert "exec-cache: 0 executable(s)" in text


def test_proto_cli_routes_runtime_paths():
    """mv2tlint accepts control-plane paths on the command line and
    the proto doctors run on them (fixture mode) — the 'lint the
    module you are editing' workflow."""
    assert lint_main([os.path.join(FIXTURES, "bad_proto.py"),
                      "--no-baseline"]) == 1
    assert lint_main([os.path.join(FIXTURES, "clean_proto.py"),
                      "--no-baseline"]) == 0
    # the committed control-plane modules pass standalone too (their
    # cross-module key peers ride along via the package default gate,
    # so standalone runs only the module-local doctors)
    assert lint_main([os.path.join(REPO, "mvapich2_tpu", "runtime",
                                   "daemon.py"), "--no-baseline"]) == 0


def test_ntrace_layout_mirrors_header():
    """The python mirror of the trace-ring geometry + NTE event table
    (trace/native.py) matches native/shm_layout.h — and a drifted
    mirror IS caught (the layout doctor bites on NTE names)."""
    from mvapich2_tpu.analysis import native as native_mod
    fs = [f for f in native_mod.NativeSourcePass().run([])
          if "NTE" in f.msg or "NTR" in f.msg]
    assert fs == []
    # drifted event table: swap two names in a synthetic mirror
    from mvapich2_tpu.analysis.native import _nte_to_name
    assert _nte_to_name("NTE_FLAT_FANIN") == "flat_fanin"
    assert _nte_to_name("NTE_BELL_RING") == "bell_ring"


# -- ISSUE 17: the metrics subsystem under the lint ratchet ---------------

def test_metrics_modules_under_lint_ratchet():
    """ISSUE 17 satellite: the telemetry modules (metrics package,
    sampler-bearing shm channel, exporter) ride the same passes as the
    datapath — in the scanned set, clean under the pvars + traceguard
    passes — and ONE seeded violation of each python class in a
    metrics-shaped module is caught (the ratchet actually bites)."""
    import mvapich2_tpu
    from mvapich2_tpu.analysis import core as acore

    pkg = os.path.dirname(mvapich2_tpu.__file__)
    modules, errors = acore.scan_paths([pkg])
    assert not errors
    names = {os.path.relpath(m.path, pkg) for m in modules}
    for need in ("metrics/__init__.py", "metrics/hist.py",
                 "metrics/ring.py", "metrics/sampler.py",
                 "metrics/export.py"):
        assert need in names, need
    from mvapich2_tpu.analysis.registry import RegistryPass
    from mvapich2_tpu.analysis.traceguard import TraceGuardPass
    met_paths = {m.path for m in modules
                 if os.path.relpath(m.path, pkg).startswith("metrics/")
                 or os.path.relpath(m.path, pkg) in
                 ("mpit.py", "transport/shm.py", "trace/mpistat.py")}
    fs = RegistryPass().run(modules)   # pvar decls are cross-module
    assert [f for f in fs if f.path in met_paths] == []
    assert [f for f in TraceGuardPass().run(
        [m for m in modules if m.path in met_paths])] == []
    # seeded: a histogram fetched by a name nothing ever declares
    # (RegistryPass) + an unguarded tracer.record beside it
    # (TraceGuardPass) in a sampler-shaped module
    bad = acore.SourceModule("metrics/bad_sampler_fixture.py", (
        "from .. import mpit\n"
        "def tick(tracer):\n"
        "    mpit.pvar('lat_hist_never_declared').rec(3)\n"
        "    tracer.record('channel', 'metrics_tick', 'i')\n"))
    assert len(RegistryPass().run(modules + [bad])) == 1
    assert len(TraceGuardPass().run([bad])) == 1


def test_metrics_layout_drift_detected(tmp_path):
    """The MV2T_MET_* segment geometry is pinned by the layout doctor:
    drifting the header's ring-row count (or any derived stride input)
    away from the trace/native.py mirror is a mechanical finding."""
    real = open(os.path.join(REPO, "native", "shm_layout.h")).read()
    hdr = tmp_path / "shm_layout.h"
    hdr.write_text(real.replace("#define MV2T_MET_RING_ROWS 256",
                                "#define MV2T_MET_RING_ROWS 255"))
    fs = native_mod.NativeSourcePass([], layout=True,
                                     layout_header=str(hdr)).run([])
    assert any("MV2T_MET_RING_ROWS" in f.msg and "disagree" in f.msg
               for f in fs), [f.msg for f in fs]
    # the committed header + mirror agree (no standing finding)
    fs = [f for f in native_mod.NativeSourcePass().run([])
          if "MV2T_MET" in f.msg]
    assert fs == []

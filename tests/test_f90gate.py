"""Parser-level gate for the generated Fortran modules.

The build host has no Fortran compiler (the gfortran-marked tests in
test_cabi.py skip), so this is the syntax gate the generated
`use mpi` / `use mpi_f08` modules compile-check against — the analog of
building the reference's src/binding/fortran/use_mpi output.  The
mutation cases prove the gate actually fires on injected syntax errors
(it is a checker, not a rubber stamp).
"""

import os
import re

import pytest

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
F90 = os.path.join(HERE, "native", "mpi", "mpi.f90")
F08 = os.path.join(HERE, "native", "mpi", "mpi_f08.f90")


def _check(text, path="<mut>"):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "f90check", os.path.join(HERE, "native", "mpi", "f90check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.check_f90(text, path)


@pytest.mark.parametrize("path", [F90, F08])
def test_generated_modules_clean(path):
    errs = _check(open(path).read(), path)
    assert errs == [], errs


def test_gate_fires_on_missing_end_subroutine():
    src = open(F08).read()
    mut = src.replace("end subroutine MPI_Barrier_f08\n", "", 1)
    assert _check(mut), "dropped 'end subroutine' not detected"


def test_gate_fires_on_keyword_typo():
    src = open(F08).read()
    mut = src.replace("integer, intent(out) :: rank",
                      "integr, intent(out) :: rank", 1)
    errs = _check(mut)
    assert any("unrecognized" in e for e in errs), errs


def test_gate_fires_on_unbalanced_parens():
    src = open(F90).read()
    mut = src.replace("subroutine mpi_init(ierror)",
                      "subroutine mpi_init(ierror", 1)
    errs = _check(mut)
    assert any("unbalanced" in e for e in errs), errs


def test_gate_fires_on_undeclared_dummy():
    src = open(F08).read()
    mut = src.replace("    integer, intent(in) :: errorcode\n", "", 1)
    errs = _check(mut)
    assert any("never declared" in e for e in errs), errs


def test_gate_fires_on_mismatched_module_name():
    src = open(F08).read()
    mut = re.sub(r"end module mpi_f08\s*$", "end module mpi_f07", src)
    errs = _check(mut)
    assert any("mismatch" in e or "unclosed" in e for e in errs), errs


def test_gate_fires_on_dangling_continuation():
    src = open(F08).read()
    mut = src.rstrip() + "\n  integer :: trailing &\n"
    assert _check(mut)

"""Model-layer tests: ring attention vs dense reference, stencil vs
single-device reference, full train step over dp x sp x tp (+MoE/ep)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from mvapich2_tpu.models import ring_attention as ra  # noqa: E402
from mvapich2_tpu.models import stencil as st  # noqa: E402
from mvapich2_tpu.models import transformer as tf  # noqa: E402
from mvapich2_tpu.parallel import MeshComm, make_mesh  # noqa: E402


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(causal):
    comm = MeshComm(make_mesh((8,), ("sp",)))
    T, H, Dh = 64, 4, 16
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (T, H, Dh), jnp.float32)
    k = jax.random.normal(kk, (T, H, Dh), jnp.float32)
    v = jax.random.normal(kv, (T, H, Dh), jnp.float32)

    ref = ra.local_attention_reference(q, k, v, causal=causal)

    out = comm.run(
        lambda qq, kk2, vv: ra.ring_attention(qq, kk2, vv, "sp",
                                              causal=causal),
        q, k, v,
        in_specs=(P("sp"), P("sp"), P("sp")),
        out_specs=P("sp"))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_stencil_matches_reference():
    comm = MeshComm(make_mesh((8,), ("z",)))
    grid, iters = 32, 3
    u0 = jnp.arange(grid ** 3, dtype=jnp.float32).reshape(grid, grid, grid)
    u0 = (u0 % 97) / 97.0
    ref = st.reference_stencil(u0, iters)
    out = st.run_stencil(comm, grid=grid, iters=iters)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_train_step_runs_and_learns():
    cfg = tf.Config(vocab=64, d_model=64, n_heads=4, n_layers=2, d_ff=128,
                    seq_len=64, batch=8, n_experts=4, lr=5e-2)
    cfg2, mesh, params, tokens, step = tf.demo_setup(cfg)
    assert dict(mesh.shape) == {"dp": 2, "sp": 2, "tp": 2}
    params, l0 = step(params, tokens)
    losses = [float(l0)]
    for _ in range(5):
        params, loss = step(params, tokens)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], f"no learning: {losses}"


@pytest.mark.slow
def test_train_step_parallel_matches_single_device():
    """The sharded train step must compute the same loss as an unsharded
    run — the correctness contract of the whole parallelism stack."""
    cfg = tf.Config(vocab=32, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                    seq_len=32, batch=4, n_experts=4, moe_layer=-1)
    # moe_layer=-1 -> dense everywhere (MoE capacity drops differ between
    # shardings by design, so compare the dense model exactly)
    key = jax.random.PRNGKey(0)
    params = tf.init_params(cfg, key)
    tokens = jax.random.randint(jax.random.PRNGKey(1),
                                (cfg.batch, cfg.seq_len), 0, cfg.vocab)

    mesh1 = tf.make_mesh((1, 1, 1), ("dp", "sp", "tp"),
                         jax.devices()[:1])
    step1 = tf.make_train_step(cfg, mesh1)
    p1 = tf.shard_params(params, cfg, mesh1)
    _, loss1 = step1(p1, jax.device_put(tokens))

    cfg8, mesh8, p8, tok8, step8 = tf.demo_setup(cfg)
    p8 = tf.shard_params(params, cfg, mesh8)
    from jax.sharding import NamedSharding
    tok8 = jax.device_put(tokens, NamedSharding(mesh8, P("dp", "sp")))
    _, loss8 = step8(p8, tok8)
    # f32 reduction-order differences across 8-way sharding: ~1e-4 rel
    np.testing.assert_allclose(float(loss1), float(loss8), rtol=1e-3)


@pytest.mark.slow
def test_moe_layer_forward_finite():
    cfg = tf.Config(vocab=32, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                    seq_len=32, batch=8, n_experts=8, moe_layer=1)
    cfg2, mesh, params, tokens, step = tf.demo_setup(cfg)
    params, loss = step(params, tokens)
    assert np.isfinite(float(loss))


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_dense(causal):
    """Ulysses all-to-all sequence parallelism produces exactly dense
    attention over the gathered sequence (the alltoall-family
    long-context strategy next to ring attention, SURVEY §5.7)."""
    from mvapich2_tpu.models import ulysses as ul

    comm = MeshComm(make_mesh((8,), ("sp",)))
    T, H, Dh = 64, 8, 16
    rng = np.random.default_rng(3)
    q, k, v = (jnp.asarray(rng.standard_normal((T, H, Dh)),
                           dtype=jnp.float32) for _ in range(3))

    def run(qs, ks, vs):
        return ul.ulysses_attention(qs, ks, vs, "sp", causal=causal)

    out = comm.run(run, q, k, v)
    want = ra.local_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_matches_ring():
    """The two sequence-parallel strategies agree with each other."""
    from mvapich2_tpu.models import ulysses as ul

    comm = MeshComm(make_mesh((8,), ("sp",)))
    T, H, Dh = 64, 8, 16
    rng = np.random.default_rng(4)
    q, k, v = (jnp.asarray(rng.standard_normal((T, H, Dh)),
                           dtype=jnp.float32) for _ in range(3))

    def run(qs, ks, vs):
        a = ul.ulysses_attention(qs, ks, vs, "sp", causal=True)
        b = ra.ring_attention(qs, ks, vs, "sp", causal=True)
        return jnp.stack([a, b])

    out = np.asarray(comm.run(run, q, k, v))
    # comm.run concatenates shard outputs on dim 0: reshape to pairs
    pairs = out.reshape(8, 2, T // 8, H, Dh)
    np.testing.assert_allclose(pairs[:, 0], pairs[:, 1], rtol=2e-4,
                               atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_matches_reference(causal):
    """The pallas flash kernel (interpret mode) is exact against the
    dense reference, including the ring-step (q0, k0) offset form."""
    from mvapich2_tpu.models.flash import flash_attention

    rng = np.random.default_rng(7)
    T, H, Dh = 256, 4, 64
    q, k, v = (jnp.asarray(rng.standard_normal((T, H, Dh)),
                           dtype=jnp.float32) for _ in range(3))
    got = flash_attention(q, k, v, causal=causal, block_q=64,
                          block_k=64, interpret=True)
    want = ra.local_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_flash_attention_ring_offsets():
    """q0/k0 parametrization: a KV block strictly in the queries' future
    is fully masked; one strictly in the past is attended unmasked."""
    from mvapich2_tpu.models.flash import flash_attention

    rng = np.random.default_rng(8)
    T, H, Dh = 128, 2, 32
    q, k, v = (jnp.asarray(rng.standard_normal((T, H, Dh)),
                           dtype=jnp.float32) for _ in range(3))
    future = flash_attention(q, k, v, causal=True, q0=0, k0=T,
                             block_q=64, block_k=64, interpret=True)
    np.testing.assert_allclose(np.asarray(future), 0.0, atol=1e-6)
    past = flash_attention(q, k, v, causal=True, q0=T, k0=0,
                           block_q=64, block_k=64, interpret=True)
    want = ra.local_attention_reference(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(past), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_with_flash_kernel():
    """Ulysses + the pallas flash kernel end-to-end on the 8-shard mesh
    (interpret mode) matches the jnp path."""
    from mvapich2_tpu.models import ulysses as ul

    comm = MeshComm(make_mesh((8,), ("sp",)))
    T, H, Dh = 128, 8, 32
    rng = np.random.default_rng(9)
    q, k, v = (jnp.asarray(rng.standard_normal((T, H, Dh)),
                           dtype=jnp.float32) for _ in range(3))

    def run(qs, ks, vs):
        return ul.ulysses_attention(qs, ks, vs, "sp", causal=True,
                                    use_flash=True, interpret=True)

    out = comm.run(run, q, k, v)
    want = ra.local_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_flash_matches_dense(causal):
    """Ring attention with the pallas flash per-step kernel (interpret
    mode) is exact against dense attention over the full sequence."""
    comm = MeshComm(make_mesh((8,), ("sp",)))
    T, H, Dh = 128, 2, 32
    rng = np.random.default_rng(11)
    q, k, v = (jnp.asarray(rng.standard_normal((T, H, Dh)),
                           dtype=jnp.float32) for _ in range(3))

    def run(qs, ks, vs):
        return ra.ring_attention_flash(qs, ks, vs, "sp", causal=causal,
                                       block_q=16, block_k=16,
                                       interpret=True)

    out = comm.run(run, q, k, v)
    want = ra.local_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-5)

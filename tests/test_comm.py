"""Communicator management tests (mirrors test/mpi/comm/)."""

import numpy as np
import pytest

from mvapich2_tpu import run_ranks
from mvapich2_tpu.core.attr import Keyval


def test_dup_isolated_context():
    def fn(comm):
        dup = comm.dup()
        assert dup.size == comm.size and dup.rank == comm.rank
        assert dup.context_id != comm.context_id
        # traffic on dup doesn't collide with comm
        peer = 1 - comm.rank
        a = np.array([1], np.int32)
        b = np.array([2], np.int32)
        ra = np.zeros(1, np.int32)
        rb = np.zeros(1, np.int32)
        r1 = comm.irecv(ra, source=peer, tag=0)
        r2 = dup.irecv(rb, source=peer, tag=0)
        dup.send(b, dest=peer, tag=0)
        comm.send(a, dest=peer, tag=0)
        r1.wait(); r2.wait()
        assert ra[0] == 1 and rb[0] == 2
        dup.free()
    run_ranks(2, fn)


def test_split():
    def fn(comm):
        color = comm.rank % 2
        sub = comm.split(color, key=comm.rank)
        assert sub.size == comm.size // 2
        rb = sub.allgather(np.array([comm.rank], np.int32))
        np.testing.assert_array_equal(rb, np.arange(color, comm.size, 2))
    run_ranks(8, fn)


def test_split_undefined():
    def fn(comm):
        sub = comm.split(None if comm.rank == 0 else 5)
        if comm.rank == 0:
            assert sub is None
        else:
            assert sub.size == comm.size - 1
    run_ranks(4, fn)


def test_split_key_reorders():
    def fn(comm):
        sub = comm.split(0, key=-comm.rank)  # reverse order
        assert sub.rank == comm.size - 1 - comm.rank
    run_ranks(4, fn)


def test_split_free_churn():
    """comm/ctxsplit.c's discipline: split/free loops must recycle
    context ids through the fused plane gather (cp_coll_gather) without
    leaking, including UNDEFINED rounds where nobody claims the bit."""
    def fn(comm):
        ids = set()
        for i in range(60):
            sub = comm.split(1, key=comm.rank)
            assert sub.size == comm.size and sub.rank == comm.rank
            ids.add(sub.context_id)
            sub.free()
            assert comm.split(None) is None
        # freed ids return to the mask: the loop reuses a tiny pool
        assert len(ids) <= 4
    run_ranks(4, fn)


def test_comm_create():
    def fn(comm):
        g = comm.group if hasattr(comm, 'group') else None
        sub_group = comm.group.incl([0, 2])
        sub = comm.create(sub_group)
        if comm.rank in (0, 2):
            assert sub.size == 2
            out = sub.allgather(np.array([comm.rank], np.int32))
            np.testing.assert_array_equal(out, [0, 2])
        else:
            assert sub is None
    run_ranks(4, fn)


def test_split_type_shared():
    def fn(comm):
        node = comm.split_type_shared()
        assert node.size == 4
        me = comm.rank
        out = node.allgather(np.array([me], np.int32))
        base = (me // 4) * 4
        np.testing.assert_array_equal(out, np.arange(base, base + 4))
    run_ranks(8, fn, nodes=[0, 0, 0, 0, 1, 1, 1, 1])


def test_attributes():
    def fn(comm):
        copies = []
        deletes = []
        kv = Keyval(
            copy_fn=lambda obj, k, extra, val: (copies.append(val) or
                                                (True, val * 2)),
            delete_fn=lambda obj, k, val, extra: deletes.append(val))
        comm.attrs.set(comm, kv, 21)
        found, val = comm.attrs.get(kv)
        assert found and val == 21
        dup = comm.dup()
        found, val = dup.attrs.get(kv)
        assert found and val == 42
        dup.free()
        assert 42 in deletes
        comm.attrs.delete(comm, kv)
        found, _ = comm.attrs.get(kv)
        assert not found
    run_ranks(2, fn)


def test_compare():
    def fn(comm):
        dup = comm.dup()
        assert comm.compare(comm) == "ident"
        assert comm.compare(dup) == "congruent"
    run_ranks(2, fn)


def test_2level_build():
    def fn(comm):
        shmem, leader = comm.build_2level()
        assert shmem.size == 2
        if comm.rank % 2 == 0:
            assert leader is not None and leader.size == 3
        else:
            assert leader is None
    run_ranks(6, fn, nodes=[0, 0, 1, 1, 2, 2])

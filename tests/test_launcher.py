"""Process-mode tests: KVS bootstrap + mpirun launcher + TCP channel
(mirrors the reference's runtests driver contract: exit 0 + 'No Errors')."""

import os
import subprocess
import time
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROG = os.path.join(REPO, "tests", "progs", "rank_prog.py")


def _run(np_, extra=None, timeout=120):
    cmd = [sys.executable, "-m", "mvapich2_tpu.run", "-np", str(np_)]
    if extra:
        cmd.extend(extra)
    cmd.extend([sys.executable, PROG])
    return subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                          timeout=timeout)


@pytest.mark.parametrize("np_", [2, 4])
def test_mpirun_rank_prog(np_):
    r = _run(np_)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout


def test_mpirun_fake_nodes_two_level():
    r = _run(4, extra=["--fake-nodes", "0,0,1,1"])
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout


def test_split_churn_over_plane():
    """comm/ctxsplit.c's split/free churn across real rank processes:
    the fused cp_coll_gather agreement plus context-id recycling."""
    prog = os.path.join(REPO, "tests", "progs", "split_churn_prog.py")
    cmd = [sys.executable, "-m", "mvapich2_tpu.run", "-np", "4",
           sys.executable, prog, "200"]
    r = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                       timeout=300)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout


def test_mpirun_failing_rank_kills_job():
    prog = os.path.join(REPO, "tests", "progs", "die_prog.py")
    cmd = [sys.executable, "-m", "mvapich2_tpu.run", "-np", "2",
           sys.executable, prog]
    r = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                       timeout=60)
    assert r.returncode != 0


def test_singleton_init():
    code = ("import sys; sys.path.insert(0, '.');"
            "from mvapich2_tpu import mpi; mpi.Init();"
            "c = mpi.COMM_WORLD; assert c.size == 1;"
            "import numpy as np;"
            "assert c.allreduce(np.ones(4))[0] == 1.0;"
            "mpi.Finalize(); print('No Errors')")
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0 and "No Errors" in r.stdout


@pytest.mark.slow
def test_runtests_driver():
    """bin/runtests: the testlist-driven conformance runner (SURVEY §4).

    CI runs the per-area subset (testlist.ci); the full 63-entry corpus
    is tests/progs/testlist, run with MV2T_CONFORMANCE_FULL=1 or
    directly via `python bin/runtests tests/progs/testlist -j4`."""
    runner = os.path.join(REPO, "bin", "runtests")
    name = ("testlist" if os.environ.get("MV2T_CONFORMANCE_FULL")
            else "testlist.ci")
    testlist = os.path.join(REPO, "tests", "progs", name)
    r = subprocess.run([sys.executable, runner, testlist, "-j", "2"],
                       cwd=REPO, capture_output=True, text=True,
                       timeout=1200)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "0 failures" in r.stdout


@pytest.mark.slow
def test_abort_kills_job():
    """MPI_Abort on one rank tears down the whole job — even ranks
    blocked in never-matching receives (MPI-3.1 §8.7; mpirun_rsh
    cleanup-on-abort). Both default and FT modes."""
    prog = os.path.join(REPO, "tests", "progs", "abort_prog.py")
    for ft_args in ([], ["--ft"]):
        t0 = time.monotonic()
        r = subprocess.run(
            [sys.executable, "-m", "mvapich2_tpu.run", "-np", "4",
             *ft_args, sys.executable, prog],
            cwd=REPO, capture_output=True, text=True, timeout=60)
        dt = time.monotonic() - t0
        assert r.returncode == 7, \
            f"MPI_Abort errorcode not propagated: rc={r.returncode}"
        assert "MPI_Abort(7)" in r.stderr, \
            f"abort banner missing ({ft_args}): {r.stderr[-300:]}"
        assert dt < 30, f"abort teardown too slow ({dt:.1f}s, {ft_args})"


def test_mpispawn_batched_failure_publication():
    """ISSUE 10 satellite (ROADMAP 3b): the mpispawn agent publishes a
    batch of rank deaths in TWO round trips (one atomic range claim +
    one mput), not two serial RTTs per event — and the claimed event
    slots stay dense and gap-free for the sequential watcher."""
    from mvapich2_tpu.runtime.mpispawn import publish_failures

    class FakeKVS:
        def __init__(self):
            self.rpcs = []
            self.data = {}
            self.seq = 0

        def add(self, key, delta=1):
            self.rpcs.append(("add", key, delta))
            self.seq += delta
            return self.seq

        def put_many(self, kv):
            self.rpcs.append(("mput", dict(kv)))
            self.data.update(kv)

        def put(self, key, val):   # must NOT be used by the batch path
            self.rpcs.append(("put", key))
            self.data[key] = val

    kvs = FakeKVS()
    publish_failures(kvs, [])
    assert kvs.rpcs == []          # no deaths, no traffic
    publish_failures(kvs, [3, 1, 7])
    assert [r[0] for r in kvs.rpcs] == ["add", "mput"]
    assert kvs.data == {"__failure_ev_0": "3", "__failure_ev_1": "1",
                        "__failure_ev_2": "7"}
    publish_failures(kvs, [5])     # next batch continues the sequence
    assert kvs.data["__failure_ev_3"] == "5"

import pytest

from mvapich2_tpu.core.group import Group, GROUP_EMPTY
from mvapich2_tpu.core.status import UNDEFINED


def test_basic():
    g = Group(range(8))
    assert g.size == 8
    assert g.world_of_rank(3) == 3
    assert g.rank_of_world(5) == 5


def test_incl_excl():
    g = Group(range(8))
    gi = g.incl([1, 3, 5])
    assert gi.world_ranks == (1, 3, 5)
    ge = g.excl([0, 7])
    assert ge.world_ranks == tuple(range(1, 7))


def test_set_ops():
    a = Group([0, 1, 2, 3])
    b = Group([2, 3, 4, 5])
    assert a.union(b).world_ranks == (0, 1, 2, 3, 4, 5)
    assert a.intersection(b).world_ranks == (2, 3)
    assert a.difference(b).world_ranks == (0, 1)


def test_translate():
    a = Group([0, 1, 2, 3])
    b = Group([3, 2, 1, 0])
    assert a.translate_ranks([0, 3], b) == [3, 0]
    c = Group([5, 6])
    assert a.translate_ranks([1], c) == [UNDEFINED]


def test_range_incl():
    g = Group(range(10))
    gr = g.range_incl([(0, 8, 2)])
    assert gr.world_ranks == (0, 2, 4, 6, 8)
    gr2 = g.range_incl([(9, 5, -2)])
    assert gr2.world_ranks == (9, 7, 5)


def test_compare():
    a = Group([0, 1, 2])
    assert a.compare(Group([0, 1, 2])) == "ident"
    assert a.compare(Group([2, 1, 0])) == "similar"
    assert a.compare(Group([0, 1])) == "unequal"
    assert GROUP_EMPTY.size == 0

"""Point-to-point tests (mirrors test/mpi/pt2pt/ of the reference suite):
eager + rendezvous, wildcards, ordering, probe, truncation, persistent."""

import numpy as np
import pytest

from mvapich2_tpu import run_ranks
from mvapich2_tpu.core import datatype as dt
from mvapich2_tpu.core.errors import MPIException, MPI_ERR_TRUNCATE
from mvapich2_tpu.core.status import ANY_SOURCE, ANY_TAG, PROC_NULL
from mvapich2_tpu.utils.config import get_config


def test_send_recv_eager():
    def fn(comm):
        if comm.rank == 0:
            comm.send(np.arange(10, dtype=np.int32), dest=1, tag=7)
        elif comm.rank == 1:
            buf = np.zeros(10, dtype=np.int32)
            st = comm.recv(buf, source=0, tag=7)
            np.testing.assert_array_equal(buf, np.arange(10))
            assert st.source == 0 and st.tag == 7 and st.count == 40
    run_ranks(2, fn)


def test_send_recv_rendezvous_large():
    n = 1 << 20  # 4 MiB of int32 — far above the eager threshold
    def fn(comm):
        if comm.rank == 0:
            comm.send(np.arange(n, dtype=np.int32), dest=1)
        elif comm.rank == 1:
            buf = np.zeros(n, dtype=np.int32)
            comm.recv(buf, source=0)
            assert buf[0] == 0 and buf[-1] == n - 1
            assert buf.sum(dtype=np.int64) == (n - 1) * n // 2
    run_ranks(2, fn)


def test_rput_protocol():
    n = 1 << 19
    def fn(comm):
        if comm.rank == 0:
            comm.send(np.arange(n, dtype=np.float64), dest=1)
        else:
            buf = np.zeros(n, dtype=np.float64)
            comm.recv(buf, source=0)
            assert buf[-1] == n - 1
    cfg = get_config()
    old = cfg["RNDV_PROTOCOL"]
    cfg.set("RNDV_PROTOCOL", "RPUT")
    try:
        run_ranks(2, fn)
    finally:
        cfg.set("RNDV_PROTOCOL", old)


def test_any_source_any_tag():
    def fn(comm):
        if comm.rank == 0:
            buf = np.zeros(1, dtype=np.int32)
            seen = set()
            for _ in range(comm.size - 1):
                st = comm.recv(buf, source=ANY_SOURCE, tag=ANY_TAG)
                assert buf[0] == st.source * 100 + st.tag
                seen.add(st.source)
            assert seen == {1, 2, 3}
        else:
            comm.send(np.array([comm.rank * 100 + comm.rank], np.int32),
                      dest=0, tag=comm.rank)
    run_ranks(4, fn)


def test_nonovertaking_order():
    def fn(comm):
        if comm.rank == 0:
            for i in range(50):
                comm.send(np.array([i], np.int64), dest=1, tag=5)
        else:
            buf = np.zeros(1, np.int64)
            for i in range(50):
                comm.recv(buf, source=0, tag=5)
                assert buf[0] == i
    run_ranks(2, fn)


def test_isend_irecv_waitall():
    def fn(comm):
        from mvapich2_tpu.core.request import waitall
        peer = 1 - comm.rank
        sbuf = np.full(64, comm.rank, np.int32)
        rbuf = np.zeros(64, np.int32)
        reqs = [comm.irecv(rbuf, source=peer, tag=1),
                comm.isend(sbuf, dest=peer, tag=1)]
        waitall(reqs)
        assert (rbuf == peer).all()
    run_ranks(2, fn)


def test_sendrecv():
    def fn(comm):
        right = (comm.rank + 1) % comm.size
        left = (comm.rank - 1) % comm.size
        sbuf = np.array([comm.rank], np.int32)
        rbuf = np.zeros(1, np.int32)
        st = comm.sendrecv(sbuf, right, 3, rbuf, left, 3)
        assert rbuf[0] == left and st.source == left
    run_ranks(4, fn)


def test_sendrecv_replace():
    def fn(comm):
        peer = 1 - comm.rank
        buf = np.array([comm.rank], np.int32)
        comm.sendrecv_replace(buf, peer, 0, peer, 0)
        assert buf[0] == peer
    run_ranks(2, fn)


def test_probe_iprobe():
    def fn(comm):
        if comm.rank == 0:
            comm.send(np.arange(5, dtype=np.float64), dest=1, tag=42)
        else:
            st = comm.probe(source=0, tag=42)
            assert st.count == 40 and st.tag == 42
            buf = np.zeros(st.count // 8, np.float64)
            comm.recv(buf, source=0, tag=42)
            assert comm.iprobe(source=0, tag=42) is None
    run_ranks(2, fn)


def test_mprobe_mrecv():
    def fn(comm):
        if comm.rank == 0:
            comm.send(np.array([123], np.int64), dest=1, tag=9)
        else:
            msg = None
            while msg is None:
                msg = comm.improbe(source=0, tag=9)
            buf = np.zeros(1, np.int64)
            st = comm.mrecv(msg, buf)
            assert buf[0] == 123 and st.source == 0
    run_ranks(2, fn)


def test_truncation_error():
    def fn(comm):
        if comm.rank == 0:
            comm.send(np.arange(10, dtype=np.int32), dest=1)
        else:
            buf = np.zeros(5, dtype=np.int32)
            with pytest.raises(MPIException) as exc:
                comm.recv(buf, source=0)
            assert exc.value.error_class == MPI_ERR_TRUNCATE
            # the first `capacity` bytes still landed
            np.testing.assert_array_equal(buf, np.arange(5))
    run_ranks(2, fn)


def test_ssend_completes_after_match():
    def fn(comm):
        if comm.rank == 0:
            comm.ssend(np.arange(4, dtype=np.int32), dest=1, tag=2)
        else:
            import time
            time.sleep(0.05)
            buf = np.zeros(4, np.int32)
            comm.recv(buf, source=0, tag=2)
            np.testing.assert_array_equal(buf, np.arange(4))
    run_ranks(2, fn)


def test_proc_null():
    def fn(comm):
        comm.send(np.zeros(1, np.int32), dest=PROC_NULL)
        st = comm.recv(np.zeros(1, np.int32), source=PROC_NULL)
        assert st.source == PROC_NULL
    run_ranks(2, fn)


def test_self_send():
    def fn(comm):
        req = comm.isend(np.array([7], np.int32), dest=comm.rank, tag=0)
        buf = np.zeros(1, np.int32)
        comm.recv(buf, source=comm.rank, tag=0)
        req.wait()
        assert buf[0] == 7
    run_ranks(2, fn)


def test_persistent_requests():
    def fn(comm):
        peer = 1 - comm.rank
        sbuf = np.zeros(8, np.int32)
        rbuf = np.zeros(8, np.int32)
        sreq = comm.send_init(sbuf, dest=peer, tag=4)
        rreq = comm.recv_init(rbuf, source=peer, tag=4)
        for it in range(3):
            sbuf[...] = comm.rank * 10 + it
            rreq.start()
            sreq.start()
            sreq.wait()
            rreq.wait()
            assert (rbuf == peer * 10 + it).all()
    run_ranks(2, fn)


def test_derived_datatype_transfer():
    def fn(comm):
        t = dt.create_vector(4, 1, 2, dt.INT).commit()
        if comm.rank == 0:
            a = np.arange(8, dtype=np.int32)
            comm.send(a, dest=1, count=1, datatype=t)
        else:
            out = np.zeros(8, dtype=np.int32)
            comm.recv(out, source=0, count=1, datatype=t)
            np.testing.assert_array_equal(out[::2], [0, 2, 4, 6])
            assert (out[1::2] == 0).all()
    run_ranks(2, fn)


def test_cancel_recv():
    def fn(comm):
        buf = np.zeros(1, np.int32)
        req = comm.irecv(buf, source=0, tag=99)
        if comm.rank == 1:
            req.cancel()
            st = req.wait()
            assert st.cancelled
        else:
            req.cancel()
            req.wait()
    run_ranks(2, fn)


def test_waitany():
    def fn(comm):
        from mvapich2_tpu.core.request import waitany
        if comm.rank == 0:
            comm.send(np.array([1], np.int32), dest=1, tag=11)
        else:
            b1 = np.zeros(1, np.int32)
            b2 = np.zeros(1, np.int32)
            r1 = comm.irecv(b1, source=0, tag=10)
            r2 = comm.irecv(b2, source=0, tag=11)
            idx = waitany([r1, r2])
            assert idx == 1 and b2[0] == 1
            r1.cancel()
    run_ranks(2, fn)


def test_eager_selfsend_buffer_reuse():
    """Eager buffer-reuse semantics on self-sends: after a completed
    eager send the user may overwrite the buffer; the receiver must see
    the ORIGINAL payload. Guards the zero-copy eager injection (the
    channel, not pack(), owns the copy — including LocalChannel
    self-delivery)."""
    def fn(comm):
        buf = np.arange(16, dtype=np.int32)
        req = comm.isend(buf, dest=comm.rank, tag=3)
        req.wait()          # eager: locally complete
        buf[:] = -1         # legal overwrite after completion
        out = np.zeros(16, np.int32)
        comm.recv(out, source=comm.rank, tag=3)
        assert (out == np.arange(16)).all(), out
    run_ranks(2, fn)


def test_cma_rndv_process_mode():
    """Large-message integrity over the native CMA rendezvous in real
    process mode (contiguous + strided + ssend + truncation + pvar)."""
    import os
    import subprocess
    import sys as _sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    prog = os.path.join(repo, "tests", "progs", "cma_rndv_prog.py")
    r = subprocess.run([_sys.executable, "-m", "mvapich2_tpu.run", "-np",
                        "2", _sys.executable, prog], cwd=repo,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout

"""HBM slot-segment collectives: ops/pallas_hbm.py kernels and the
HBMSlotChannel co-residence path (more ranks than devices — the
mpirun-on-one-chip model). On CPU the kernels run in pallas interpret
mode and the channel binds a 1-device mesh explicitly."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mvapich2_tpu import run_ranks
from mvapich2_tpu.ops import pallas_hbm as ph
from mvapich2_tpu.utils.config import get_config


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("layout", ["planar", "interleaved"])
@pytest.mark.parametrize("mean", [False, True])
def test_fused_reduce_to_slot(layout, mean):
    R, M, L = 4, 8, 128
    key = jax.random.PRNGKey(0)
    if layout == "planar":
        x = jax.random.normal(key, (R, M, L), jnp.float32)
        ref = np.asarray(x).sum(axis=0)
    else:
        x = jax.random.normal(key, (M, R, L), jnp.float32)
        ref = np.asarray(x).sum(axis=1)
    if mean:
        ref = ref / R
    out = ph.fused_reduce_to_slot(x, layout=layout, mean=mean, block_m=4)
    assert out.shape == (M, L)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5,
                               atol=1e-4)


@pytest.mark.parametrize("donate", [False, True])
def test_fused_allreduce_broadcast(donate):
    R, M, L = 8, 16, 128
    x = jax.random.normal(jax.random.PRNGKey(1), (M, R, L), jnp.float32)
    ref = np.broadcast_to(
        np.asarray(x).sum(axis=1, keepdims=True), (M, R, L))
    out = ph.fused_allreduce(x, block_m=8, donate=donate)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5,
                               atol=1e-4)


def test_hbm_slot_allreduce_ragged():
    # n not a multiple of 128: the pad must not leak into the result
    R, n = 3, 1000
    bufs = jnp.asarray(np.random.default_rng(2).normal(size=(R, n)),
                       jnp.float32)
    out = ph.hbm_slot_allreduce(bufs)
    assert out.shape == (n,)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(bufs).sum(axis=0), rtol=1e-5,
                               atol=1e-4)


def test_pack_unpack_roundtrip():
    R, n = 4, 512
    bufs = jnp.arange(R * n, dtype=jnp.float32).reshape(R, n)
    np.testing.assert_array_equal(
        np.asarray(ph.unpack_interleaved(ph.pack_interleaved(bufs))),
        np.asarray(bufs))


def test_bench_candidates_cover_both_kernels():
    cands = ph.bench_candidates(M=2048, R=8)
    names = [c[0] for c in cands]
    assert any(n.startswith("hbm_slot_reduce") for n in names)
    assert any(n.startswith("hbm_fused_bcast") for n in names)
    m = 2048 * 128 * 4
    for name, _, traffic, chains in cands:
        assert traffic == (9 * m if "slot" in name else 16 * m)
        # only shape-preserving ops may be timed as a chain
        assert chains == name.startswith("hbm_fused")


# ---------------------------------------------------------------------------
# the co-residence channel (ranks > devices)
# ---------------------------------------------------------------------------

def _one_device_mesh():
    from mvapich2_tpu.parallel.mesh import make_mesh
    return make_mesh((1,), ("x",), jax.devices()[:1])


def _force_device(names):
    cfg = get_config()
    for n in names:
        cfg.set(f"{n}_ALGO", "device")


def _unforce(names):
    cfg = get_config()
    for n in names:
        cfg.set(f"{n}_ALGO", "")


@pytest.mark.parametrize("nranks", [4, 5])
def test_slot_channel_allreduce(nranks):
    _force_device(["ALLREDUCE"])

    def fn(comm):
        assert type(comm.device_channel).__name__ == "HBMSlotChannel"
        sb = (np.arange(300, dtype=np.float32) + comm.rank)
        rb = comm.allreduce(sb)
        expected = (np.arange(300, dtype=np.float32) * comm.size
                    + sum(range(comm.size)))
        np.testing.assert_allclose(rb, expected, rtol=1e-6)
        # max (the non-pallas reduction path)
        from mvapich2_tpu.core import op as opmod
        mx = comm.allreduce(np.full(16, comm.rank, np.float32),
                            op=opmod.MAX)
        np.testing.assert_array_equal(mx, comm.size - 1)
    try:
        run_ranks(nranks, fn, device_mesh=_one_device_mesh())
    finally:
        _unforce(["ALLREDUCE"])


def test_slot_channel_bcast_allgather_alltoall_rsb():
    names = ["BCAST", "ALLGATHER", "ALLTOALL", "REDUCE_SCATTER"]
    _force_device(names)

    def fn(comm):
        p = comm.size
        # bcast from a nonzero root
        buf = (np.arange(130, dtype=np.float32) * 3 if comm.rank == 2
               else np.zeros(130, np.float32))
        comm.bcast(buf, root=2)
        np.testing.assert_allclose(buf,
                                   np.arange(130, dtype=np.float32) * 3)
        # allgather
        sb = np.full(7, comm.rank, np.float32)
        rb = np.zeros(7 * p, np.float32)
        comm.allgather(sb, rb)
        np.testing.assert_array_equal(
            rb, np.repeat(np.arange(p, dtype=np.float32), 7))
        # alltoall
        sb = np.arange(p * 3, dtype=np.float32) + 100 * comm.rank
        rb = np.zeros(p * 3, np.float32)
        comm.alltoall(sb, rb)
        expected = np.concatenate(
            [np.arange(comm.rank * 3, comm.rank * 3 + 3) + 100 * src
             for src in range(p)]).astype(np.float32)
        np.testing.assert_array_equal(rb, expected)
        # reduce_scatter_block
        sb = np.arange(p * 5, dtype=np.float32) + comm.rank
        rb = comm.reduce_scatter_block(sb, count=5)
        base = np.arange(comm.rank * 5, (comm.rank + 1) * 5,
                         dtype=np.float32)
        np.testing.assert_allclose(rb, base * p + sum(range(p)))
    try:
        run_ranks(4, fn, device_mesh=_one_device_mesh())
    finally:
        _unforce(names)


def test_slot_channel_device_resident_zero_copy():
    """Device-resident buffers: every rank's allreduce result is the
    SAME device array (the zero-copy shared slot)."""
    _force_device(["ALLREDUCE"])
    got = {}

    def fn(comm):
        sb = jnp.asarray(np.full(256, float(comm.rank + 1), np.float32))
        out = comm.allreduce(sb, recvbuf=None)
        got[comm.rank] = out
        np.testing.assert_allclose(
            np.asarray(out),
            np.full(256, sum(range(1, comm.size + 1)), np.float32))
    try:
        run_ranks(3, fn, device_mesh=_one_device_mesh())
    finally:
        _unforce(["ALLREDUCE"])
    assert got[0] is got[1] is got[2]

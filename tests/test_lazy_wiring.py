"""Lazy-wiring correctness sweep (PR 9 tentpole).

First contact with an unwired peer through every datapath shape —
eager send, rendezvous, flat collective, arena collective — through
BOTH ABIs and np{2,4,8}, plus the kill-during-wire chaos site
(MV2T_FAULTS=wire:crash) proving lease containment still holds when a
rank dies inside the wire step."""

import os
import shutil
import subprocess
import sys
import tempfile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROG = os.path.join(REPO, "tests", "progs", "lazywire_prog.py")
CPROG = os.path.join(REPO, "tests", "progs", "lazywire_test.c")


def _mpirun(np_, argv, env=None, timeout=300):
    e = dict(os.environ)
    e.setdefault("JAX_PLATFORMS", "cpu")
    if env:
        e.update(env)
    return subprocess.run(
        [sys.executable, "-m", "mvapich2_tpu.run", "-np", str(np_),
         *argv],
        cwd=REPO, env=e, capture_output=True, text=True, timeout=timeout)


@pytest.mark.parametrize("np_", [2, 4])
@pytest.mark.parametrize("mode", ["eager", "rndv", "flat", "arena"])
def test_lazy_first_contact_python(mode, np_):
    """Python ABI: first contact through each shape is correct, the
    node wires exactly once, attributed to wiring_lazy."""
    r = _mpirun(np_, [sys.executable, PROG, mode])
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout
    assert r.stdout.count("wired=lazy OK") == np_


@pytest.mark.parametrize("mode", ["eager", "rndv", "flat", "arena"])
@pytest.mark.slow
def test_lazy_first_contact_python_np8(mode):
    r = _mpirun(8, [sys.executable, PROG, mode])
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout
    assert r.stdout.count("wired=lazy OK") == 8


def test_eager_wiring_mode_preserved():
    """MV2T_LAZY_WIRING=0 restores the eager-at-Init semantics: the
    wire happens at bootstrap (wiring_eager), never lazily."""
    r = _mpirun(2, [sys.executable, PROG, "flat"],
                env={"MV2T_LAZY_WIRING": "0"})
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout
    assert r.stdout.count("wired=eager OK") == 2


@pytest.mark.skipif(shutil.which("gcc") is None, reason="no C toolchain")
@pytest.mark.parametrize("np_", [2, 4])
@pytest.mark.parametrize("mode", ["eager", "rndv", "flat", "arena"])
def test_lazy_first_contact_cabi(mode, np_):
    """C ABI: the same first-contact sweep through libmpi.so — world
    build AND wire both deferred past MPI_Init."""
    out = os.path.join(tempfile.mkdtemp(), "lazywire_test")
    rc = subprocess.run([os.path.join(REPO, "bin", "mpicc"), CPROG,
                         "-o", out], capture_output=True, text=True,
                        timeout=180)
    assert rc.returncode == 0, f"mpicc: {rc.stdout}\n{rc.stderr}"
    r = _mpirun(np_, [out, mode])
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout


@pytest.mark.slow
@pytest.mark.skipif(shutil.which("gcc") is None, reason="no C toolchain")
def test_lazy_first_contact_cabi_np8():
    out = os.path.join(tempfile.mkdtemp(), "lazywire_test")
    rc = subprocess.run([os.path.join(REPO, "bin", "mpicc"), CPROG,
                         "-o", out], capture_output=True, text=True,
                        timeout=180)
    assert rc.returncode == 0, f"mpicc: {rc.stdout}\n{rc.stderr}"
    r = _mpirun(8, [out, "flat"], timeout=420)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout


def test_kill_during_wire_contained():
    """Chaos: rank 1 crashes INSIDE the wire step (site=wire). The
    survivors' blocking wire gate must unwind with
    MPIX_ERR_PROC_FAILED via the lease scan / failure events — never
    hang, never complete a half-wired collective. The chaos prog
    handles the error, shrinks, and finishes (its normal contract)."""
    prog = os.path.join(REPO, "tests", "progs", "chaos_prog.py")
    r = _mpirun(
        4, [sys.executable, prog],
        env={"MV2T_FAULTS": "wire@1:crash",
             "MV2T_PEER_TIMEOUT": "3",
             "MV2T_FT_WATCHER": "0",       # lease-only detection
             "MPIEXEC_ALLOW_FAULT": "1",
             "MV2T_CHAOS_PHASES": "flat,arena"},
        timeout=420)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout
    # the survivor must have seen a contained process-failure error
    assert "err=" in r.stdout

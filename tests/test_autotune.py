"""Autotuner: measured tables + device crossovers feeding the tuning layer."""

import json
import os

import numpy as np
import pytest

from mvapich2_tpu import autotune
from mvapich2_tpu.coll import tuning
from mvapich2_tpu.runtime.universe import run_ranks


@pytest.fixture(autouse=True)
def _restore_tables():
    saved_t = dict(tuning._PROFILE_TABLES)
    saved_c = dict(tuning._DEVICE_CROSSOVERS)
    yield
    tuning._PROFILE_TABLES.clear()
    tuning._PROFILE_TABLES.update(saved_t)
    tuning._DEVICE_CROSSOVERS.clear()
    tuning._DEVICE_CROSSOVERS.update(saved_c)


def test_profile_comm_measures_and_agrees():
    holder = {}

    def app(comm):
        p = autotune.profile_comm(comm, colls=("allreduce",),
                                  sizes=[1024, 16384], reps=2)
        holder[comm.rank] = p

    run_ranks(4, app, device_mesh=True)
    # identical profile on every rank (built from agreed max-times)
    p0 = holder[0]
    for r in range(1, 4):
        assert holder[r] == p0
    table = p0["tables"]["allreduce"]["small"]
    assert table[-1][0] is None          # open last bin
    algos = {a for _, a in table}
    assert algos <= set(tuning.ALGOS["allreduce"])
    assert "device" in p0["raw"]["allreduce"]  # device transport measured


def test_save_load_round_trip(tmp_path):
    prof = {"tables": {"allreduce": {"small": [[4096, "rd"],
                                               [None, "ring"]]}},
            "device_crossovers": {"allreduce": 65536}}
    path = str(tmp_path / "prof.json")
    autotune.save_profile(prof, path)
    assert autotune.load_profile_file(path)
    # installed: lookup follows the measured rows, crossover overrides cvar
    class FakeComm:
        size = 8
    assert tuning._lookup("allreduce", FakeComm(), 1000) == "rd"
    assert tuning._lookup("allreduce", FakeComm(), 10**6) == "ring"
    assert tuning.device_crossover("allreduce", FakeComm()) == 65536


def test_arch_mismatch_rejected(tmp_path):
    path = str(tmp_path / "other.json")
    with open(path, "w") as f:
        json.dump({"arch_key": "tpu:v9:4096", "profile": {"tables": {}},
                   "format": "mv2t-tuning-profile-v1"}, f)
    assert not autotune.load_profile_file(path)


def test_committed_ci_profile_exists_and_loads():
    """The generated artifact for the CI mesh is committed and valid."""
    path = os.path.join(autotune.PROFILE_DIR, "cpu_cpu_8.json")
    assert os.path.exists(path), "committed CI tuning profile missing"
    doc = json.load(open(path))
    assert doc["format"] == "mv2t-tuning-profile-v1"
    assert doc["arch_key"] == "cpu:cpu:8"
    assert "allreduce" in doc["profile"]["tables"]
    # loads when arch matches (conftest runs the suite on the cpu:8 mesh)
    autotune._default_attempted = False
    assert autotune.load_profile_file(path)


def test_mpit_autotune_name_exists():
    """tuning.py's docstring names mpit.autotune — it must resolve."""
    from mvapich2_tpu import mpit
    assert mpit.autotune.profile_comm is autotune.profile_comm


def test_committed_profile_carries_device_tiers():
    """The --device sweep's boundaries are committed for the CI arch
    and flow through load_profile into coll/tuning.device_tier."""
    from mvapich2_tpu.coll import tuning
    path = os.path.join(autotune.PROFILE_DIR, "cpu_cpu_8.json")
    doc = json.load(open(path))
    dc = doc["profile"]["device_crossovers"]
    assert "dev_tier_vmem_max" in dc and "dev_tier_xla_min" in dc
    assert doc["profile"]["kernel_params"]["ici_chunk_bytes"] > 0
    saved = dict(tuning._DEVICE_CROSSOVERS)
    saved_kp = dict(tuning._KERNEL_PARAMS)
    tuning._DEVICE_CROSSOVERS.clear()
    tuning._KERNEL_PARAMS.clear()
    try:
        assert autotune.load_profile_file(path)
        # the measured CPU crossovers route this arch's band to XLA
        # above xla_min — honest: interpreted kernels lose to XLA here
        assert tuning._DEVICE_CROSSOVERS["dev_tier_xla_min"] == \
            dc["dev_tier_xla_min"]
        assert tuning.kernel_param(
            "ici_chunk_bytes", -1) == \
            doc["profile"]["kernel_params"]["ici_chunk_bytes"]
    finally:
        tuning._DEVICE_CROSSOVERS.clear()
        tuning._DEVICE_CROSSOVERS.update(saved)
        tuning._KERNEL_PARAMS.clear()
        tuning._KERNEL_PARAMS.update(saved_kp)


def test_merge_device_profile_roundtrip(tmp_path):
    """merge_device_profile folds a sweep fragment into an existing
    arch profile without clobbering the host tables."""
    path = str(tmp_path / "prof.json")
    autotune.save_profile(
        {"tables": {"allreduce": {"small": [[None, "rd"]]}},
         "device_crossovers": {"allreduce": 1234}}, path)
    frag = {"device_crossovers": {"dev_tier_vmem_max": 64,
                                  "dev_tier_xla_min": 4096},
            "kernel_params": {"ici_chunk_bytes": 2048},
            "raw_device_tiers": {"vmem": {"64": 0.1}}}
    out = autotune.merge_device_profile(frag, path)
    assert out == path
    doc = json.load(open(path))
    prof = doc["profile"]
    assert prof["tables"]["allreduce"]["small"] == [[None, "rd"]]
    assert prof["device_crossovers"] == {
        "allreduce": 1234, "dev_tier_vmem_max": 64,
        "dev_tier_xla_min": 4096}
    assert prof["kernel_params"]["ici_chunk_bytes"] == 2048
    assert prof["raw_device_tiers"]["vmem"]["64"] == 0.1

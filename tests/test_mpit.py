"""MPI_T introspection tests — modeled on the reference's test/mpi/mpi_t
area (cvarwrite, getindex, mpit_vars) plus startup-timestamp checks."""

import numpy as np

from mvapich2_tpu import mpit
from mvapich2_tpu.runtime.universe import run_ranks
from mvapich2_tpu.utils import timestamps
from mvapich2_tpu.utils.config import get_config


def test_cvar_enumeration_and_info():
    n = mpit.cvar_get_num()
    assert n >= 5   # core knobs at minimum
    names = set()
    for i in range(n):
        info = mpit.cvar_get_info(i)
        assert info["name"] and info["env"].startswith("MV2T_")
        assert info["type"] in ("int", "bool", "str", "float")
        names.add(info["name"])
    assert "EAGER_THRESHOLD" in names
    assert "RNDV_PROTOCOL" in names


def test_cvar_read_write_roundtrip():
    i = mpit.cvar_get_index("EAGER_THRESHOLD")
    old = mpit.cvar_read(i)
    try:
        mpit.cvar_write(i, 1234)
        assert mpit.cvar_read(i) == 1234
        assert get_config()["EAGER_THRESHOLD"] == 1234  # same registry
    finally:
        mpit.cvar_write(i, old)


def test_pvar_counters_grow_with_traffic():
    pv_names = mpit._pvars.names()
    assert "recvq_match_attempts" in pv_names
    assert "pt2pt_eager_sent" in pv_names

    sess = mpit.pvar_session_create()
    h_match = sess.handle_alloc("recvq_match_attempts")
    h_eager = sess.handle_alloc("pt2pt_eager_sent")
    h_bytes = sess.handle_alloc("pt2pt_bytes_sent")
    sess.start(h_match)
    sess.start(h_eager)
    sess.start(h_bytes)

    def body(comm):
        buf = np.full(64, comm.rank, dtype=np.float64)
        out = np.zeros(64, dtype=np.float64)
        comm.sendrecv(buf, (comm.rank + 1) % comm.size, 7,
                      out, (comm.rank - 1) % comm.size, 7)
        return True

    run_ranks(4, body)
    assert sess.read(h_match) >= 4          # one recv match per rank
    assert sess.read(h_eager) >= 4          # 64*8B rides eager
    assert sess.read(h_bytes) >= 4 * 64 * 8
    sess.handle_free(h_match)


def test_pvar_session_isolation():
    pv = mpit.pvar("test_isolated_counter", mpit.PVAR_CLASS_COUNTER,
                   "test", "session isolation probe")
    s1 = mpit.pvar_session_create()
    s2 = mpit.pvar_session_create()
    h1 = s1.handle_alloc("test_isolated_counter")
    s1.start(h1)
    pv.inc(5)
    h2 = s2.handle_alloc("test_isolated_counter")
    s2.start(h2)
    pv.inc(2)
    assert s1.read(h1) == 7
    assert s2.read(h2) == 2


def test_coll_algorithm_timers():
    def body(comm):
        out = comm.allreduce(np.ones(16))
        assert out[0] == comm.size
        return True

    sess = mpit.pvar_session_create()
    run_ranks(4, body)
    # some allreduce algorithm timer + counter must now exist and be > 0
    names = [n for n in mpit._pvars.names()
             if n.startswith("coll_allreduce") and n.endswith("_calls")]
    assert names, mpit._pvars.names()
    assert any(mpit._pvars.get(n).read() > 0 for n in names)
    tnames = [n.replace("_calls", "_time") for n in names]
    assert all(mpit._pvars.get(n).klass == mpit.PVAR_CLASS_TIMER
               for n in tnames)


def test_categories():
    cats = mpit.category_names()
    assert "pt2pt" in cats and "coll" in cats
    i = cats.index("pt2pt")
    info = mpit.category_get_info(i)
    assert info["num_cvars"] >= 1
    assert "recvq_match_attempts" in info["pvars"]


def test_channel_and_protocol_pvars_in_categories():
    """The per-channel byte/message counters and the trace/watchdog pvars
    enumerate under category_get_info (mv2_mpit.c channel-counter
    discipline)."""
    import mvapich2_tpu.trace  # noqa: F401  (declares the trace pvars)

    def body(comm):
        comm.sendrecv(np.ones(8), (comm.rank + 1) % comm.size, 1,
                      np.zeros(8), (comm.rank - 1) % comm.size, 1)
        return True

    run_ranks(2, body)
    cats = mpit.category_names()
    assert "channel" in cats and "trace" in cats
    info = mpit.category_get_info(cats.index("channel"))
    assert "chan_local_msgs_sent" in info["pvars"]
    assert "chan_local_bytes_sent" in info["pvars"]
    assert mpit.pvar("chan_local_msgs_sent").read() > 0
    assert mpit.pvar("chan_local_bytes_sent").read() >= 8 * 8
    tinfo = mpit.category_get_info(cats.index("trace"))
    assert "stall_watchdog_trips" in tinfo["pvars"]
    assert "TRACE" in tinfo["cvars"] and "STALL_TIMEOUT" in tinfo["cvars"]
    ptinfo = mpit.category_get_info(cats.index("pt2pt"))
    assert "pt2pt_eager_sent" in ptinfo["pvars"]
    assert "pt2pt_rndv_sent" in ptinfo["pvars"]


def test_analysis_category_knobs():
    """The mv2t-analyze knobs enumerate under the 'analysis' category:
    the MV2T_LOCKCHECK cvar plus the checker/monitor pvars (satellite of
    the mv2tlint PR) — and lint_findings_baseline is a sourced LEVEL
    pvar tracking the committed suppression count."""
    cats = mpit.category_names()
    assert "analysis" in cats
    info = mpit.category_get_info(cats.index("analysis"))
    assert "LOCKCHECK" in info["cvars"]
    for pv in ("lint_findings_baseline", "lockcheck_cycles",
               "lockcheck_edges"):
        assert pv in info["pvars"]
    pv = mpit._pvars.get("lint_findings_baseline")
    assert pv.klass == mpit.PVAR_CLASS_LEVEL
    from mvapich2_tpu.analysis.core import load_baseline
    assert pv.read() == float(len(load_baseline().entries))
    assert mpit.pvar_get_info(
        mpit.pvar_get_index("lint_findings_baseline"))["continuous"]
    for pv_name in ("lockcheck_cycles", "lockcheck_edges"):
        assert mpit._pvars.get(pv_name).klass == mpit.PVAR_CLASS_COUNTER


def test_sourced_pvar_rebound_across_restart():
    """MPI_T session vs a universe restart: a sourced pvar's callable is
    rebound on re-declare (fresh universe), so a session created after
    the restart reads the NEW source — the stale source must not
    survive. Mirrors how progress/cplane counters rebind when process
    mode re-initializes."""
    old_engine = {"polls": 7.0}
    pv = mpit.pvar("test_restart_sourced", mpit.PVAR_CLASS_COUNTER,
                   "test", "restart rebind probe",
                   source=lambda: old_engine["polls"])
    sess = mpit.pvar_session_create()
    h = sess.handle_alloc("test_restart_sourced")
    sess.start(h)
    old_engine["polls"] = 10.0
    assert sess.read(h) == 3.0          # delta against the session base

    # "universe restart": a new owner re-declares with its own source;
    # the registry must swap callables in place (same PVar object)
    new_engine = {"polls": 100.0}
    pv2 = mpit.pvar("test_restart_sourced", mpit.PVAR_CLASS_COUNTER,
                    "test", "restart rebind probe",
                    source=lambda: new_engine["polls"])
    assert pv2 is pv
    assert pv.read() == 100.0           # stale source is gone
    old_engine["polls"] = 99999.0       # the dead universe moves on
    assert pv.read() == 100.0
    sess2 = mpit.pvar_session_create()
    h2 = sess2.handle_alloc("test_restart_sourced")
    sess2.start(h2)
    new_engine["polls"] = 130.0
    assert sess2.read(h2) == 30.0


def test_highwatermark_pvar_session_semantics():
    """Watermark (and level) pvars read INSTANTANEOUS values through a
    session — a delta against the session base would be meaningless —
    and survive a run_ranks restart monotonically."""
    pv = mpit.pvar("test_hwm_probe", mpit.PVAR_CLASS_HIGHWATERMARK,
                   "test", "watermark session probe")
    pv.mark(5.0)
    sess = mpit.pvar_session_create()
    h = sess.handle_alloc("test_hwm_probe")
    sess.start(h)
    assert sess.read(h) == 5.0          # not 0: no delta for watermarks
    pv.mark(3.0)
    assert sess.read(h) == 5.0          # lower mark never regresses
    pv.mark(9.0)
    assert sess.read(h) == 9.0
    # level pvars behave the same through the restart of the owning
    # universe: nbc_scheds_active returns to 0 after each run completes
    run_ranks(2, lambda c: c.ibarrier().wait() or True)
    run_ranks(2, lambda c: c.ibarrier().wait() or True)
    assert mpit.pvar("nbc_scheds_active").read() == 0


def test_progress_poll_pvar():
    i = mpit.pvar_get_index("progress_polls")
    info = mpit.pvar_get_info(i)
    assert info["continuous"] is False
    before = mpit._pvars.get("progress_polls").read()

    def body(comm):
        comm.barrier()
        return True

    run_ranks(2, body)
    assert mpit._pvars.get("progress_polls").read() > before


def test_dump_renders():
    text = mpit.dump()
    assert "recvq_match_attempts" in text


def test_startup_timestamps():
    get_config().set("STARTUP_TIMING", True)
    try:
        ts = timestamps.get_timestamps()
        ts.reset()
        with ts.phase("outer"):
            with ts.phase("inner"):
                pass
        text = ts.render()
        assert "outer" in text and "inner" in text
        # inner is nested one level deeper
        outer_line = next(l for l in text.splitlines() if "outer" in l)
        inner_line = next(l for l in text.splitlines() if "inner" in l)
        assert len(inner_line) - len(inner_line.lstrip()) > \
            len(outer_line) - len(outer_line.lstrip())
    finally:
        get_config().set("STARTUP_TIMING", False)
        timestamps.get_timestamps().reset()


def test_timestamps_disabled_no_overhead():
    ts = timestamps.get_timestamps()
    ts.reset()
    assert not ts.enabled
    with ts.phase("should_not_record"):
        pass
    assert "should_not_record" not in ts.render()


def test_fastpath_category():
    """The fast-path observability counters (ISSUE 5 satellite)
    enumerate under category "fastpath": hit/fallback/wait-outcome
    counters shared by the C ABI's fastpath.c and the python flat
    collective tier, plus the FP_COLL_MAX collective-tier cap cvar
    under "coll"."""
    import mvapich2_tpu.coll.tuning    # noqa: F401  (declares coll cvars)
    import mvapich2_tpu.transport.shm  # noqa: F401  (declares fp pvars)
    cats = mpit.category_names()
    assert "fastpath" in cats
    info = mpit.category_get_info(cats.index("fastpath"))
    for pv in ("fp_hits", "fp_gil_takes", "fp_fallback_dtype",
               "fp_fallback_comm", "fp_fallback_size",
               "fp_fallback_plane", "fp_coll_flat", "fp_coll_flat2",
               "fp_coll_sched", "fp_wait_spin", "fp_wait_bell",
               "fp_flat_progress"):
        assert pv in info["pvars"], pv
        assert mpit._pvars.get(pv).klass == mpit.PVAR_CLASS_COUNTER
    cinfo = mpit.category_get_info(cats.index("coll"))
    assert "FP_COLL_MAX" in cinfo["cvars"]
    # hierarchical flat2 tier cvars (ISSUE 11)
    assert "FLAT2" in cinfo["cvars"]
    assert "FLAT2_GROUP" in cinfo["cvars"]


def test_fastpath_pvars_observable():
    """The fast-path counters move for a real flat-tier workload (the
    plane only exists in process mode, so this drives the launcher)."""
    import subprocess
    import sys as _sys
    from mvapich2_tpu.transport.shm import _load_native
    if _load_native() is None:
        import pytest
        pytest.skip("native plane unavailable")
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    prog = os.path.join(repo, "tests", "progs", "fp_pvar_prog.py")
    r = subprocess.run([_sys.executable, "-m", "mvapich2_tpu.run", "-np",
                        "2", _sys.executable, prog], cwd=repo,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout
    assert "did not move" not in r.stdout


def test_plane_pvars_observable():
    """The C plane's counters (cp_stats) surface as MPI_T pvars — the
    fast-path hit-rate for a workload is observable through a session
    in-job (mv2_mpit.c:17-39 channel-counter discipline). The plane only
    exists in process mode, so this drives the launcher."""
    import subprocess
    import sys as _sys
    from mvapich2_tpu.transport.shm import _load_native
    if _load_native() is None:
        import pytest
        pytest.skip("native plane unavailable")
    assert mpit.pvar_get_index("cplane_eager_tx") >= 0
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    prog = os.path.join(repo, "tests", "progs", "pvar_plane_prog.py")
    r = subprocess.run([_sys.executable, "-m", "mvapich2_tpu.run", "-np",
                        "2", _sys.executable, prog], cwd=repo,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout
    assert "did not move" not in r.stdout


def test_device_category():
    """The device-collective engine knobs + fallback counters (ISSUE 8
    satellite) enumerate under category "device": the ICI kernel cvars
    (chunk bytes, pipeline depth, direction, interpret) and the tier /
    fallback pvar family shared by ops/pallas_ici, ops/pallas_ring and
    coll/device — declared in mpit.py so tools see them before any
    jax import."""
    cats = mpit.category_names()
    assert "device" in cats
    info = mpit.category_get_info(cats.index("device"))
    for cv in ("ICI_CHUNK_BYTES", "ICI_PIPELINE_DEPTH", "ICI_BIDIR",
               "ICI_INTERPRET", "DEV_TIER_VMEM_MAX", "DEV_TIER_XLA_MIN",
               "QUANT_COLL", "QUANT_BLOCK", "DEV_TIER_QUANT_MIN"):
        assert cv in info["cvars"], cv
    for pv in ("dev_coll_fallback_size", "dev_coll_fallback_dtype",
               "dev_coll_fallback_shape", "dev_coll_fallback_platform",
               "dev_coll_tier_vmem", "dev_coll_tier_hbm",
               "dev_coll_tier_quant", "dev_coll_quant_bytes_saved"):
        assert pv in info["pvars"], pv
        assert mpit._pvars.get(pv).klass == mpit.PVAR_CLASS_COUNTER
    # the per-tier effbw watermark family covers the quant tier too
    assert mpit._pvars.get("dev_effbw_quant").klass == \
        mpit.PVAR_CLASS_HIGHWATERMARK
    # cvar surface round-trips through the indexed MPI_T view
    i = mpit.cvar_get_index("ICI_CHUNK_BYTES")
    assert mpit.cvar_get_info(i)["name"] == "ICI_CHUNK_BYTES"
    assert int(mpit.cvar_read(i)) > 0


def test_device_fallback_pvars_move():
    """A pvar session sees the fallback family move when a device
    collective is rejected to the XLA lowering (the once-silent cliff,
    now MPI_T-visible)."""
    from mvapich2_tpu.ops._compat import note_fallback
    sess = mpit.pvar_session_create()
    h = sess.handle_alloc("dev_coll_fallback_size")
    sess.start(h)
    note_fallback("allreduce", "size", 1 << 23, "float32")
    assert sess.read(h) >= 1


def test_device_category_one_sided():
    """The one-sided lane (ISSUE 16) declares its surface in mpit.py
    too: the RMA chunk cvar and the tier/fallback/sync pvar family
    ops/pallas_rma and rma/device share, under the same "device"
    category so mpistat/watchdog enumerate them with the collective
    ones."""
    cats = mpit.category_names()
    info = mpit.category_get_info(cats.index("device"))
    for cv in ("RMA_CHUNK_BYTES", "DEV_RMA_RDMA_MIN",
               "DEV_RMA_QUANT_MIN"):
        assert cv in info["cvars"], cv
    for pv in ("dev_rma_tier_rdma", "dev_rma_tier_quant",
               "dev_rma_tier_epoch", "dev_rma_fallback_noncontig",
               "dev_rma_fallback_platform", "dev_rma_fallback_size",
               "dev_rma_fallback_dtype", "dev_rma_flush",
               "dev_rma_wire_bytes"):
        assert pv in info["pvars"], pv
        assert mpit._pvars.get(pv).klass == mpit.PVAR_CLASS_COUNTER
    # RMA_CHUNK_BYTES round-trips and defaults to "inherit ici" (<= 0)
    i = mpit.cvar_get_index("RMA_CHUNK_BYTES")
    assert mpit.cvar_get_info(i)["name"] == "RMA_CHUNK_BYTES"
    assert int(mpit.cvar_read(i)) <= 0


def test_device_rma_pvars_move():
    """The one-sided fallback counters move through a pvar session
    when an op is rejected to the epoch compiler."""
    from mvapich2_tpu.ops.pallas_rma import note_rma_fallback
    sess = mpit.pvar_session_create()
    h = sess.handle_alloc("dev_rma_fallback_noncontig")
    sess.start(h)
    note_rma_fallback("put", "noncontig", 4096)
    assert sess.read(h) >= 1

"""Topology tests — modeled on the reference's test/mpi/topo area
(cartmap, cartshift, cartsuball, dims, graphmap, dgraph_adjacent,
neighb_coll)."""

import numpy as np
import pytest

from mvapich2_tpu.core import topo
from mvapich2_tpu.core.errors import MPIException
from mvapich2_tpu.core.status import PROC_NULL
from mvapich2_tpu.runtime.universe import run_ranks


def test_dims_create():
    assert sorted(topo.dims_create(12, 2), reverse=True) == [4, 3]
    assert topo.dims_create(8, 3) == [2, 2, 2]
    assert topo.dims_create(7, 1) == [7]
    assert topo.dims_create(6, 2, [3, 0]) == [3, 2]
    assert topo.dims_create(1, 2) == [1, 1]
    with pytest.raises(MPIException):
        topo.dims_create(7, 2, [2, 0])  # 7 not divisible by 2


def test_cart_coords_rank_roundtrip():
    t = topo.CartTopology([2, 3, 4], [True, False, True])
    for r in range(24):
        assert t.rank_of(t.coords_of(r)) == r
    # periodic wrap in dim 0 and 2, PROC_NULL off-edge in dim 1
    assert t.rank_of([2, 0, 0]) == t.rank_of([0, 0, 0])
    assert t.rank_of([0, 3, 0]) == PROC_NULL
    assert t.rank_of([0, 0, 4]) == t.rank_of([0, 0, 0])


def test_cart_create_shift_ring():
    def body(comm):
        cart = comm.cart_create([comm.size], periods=[True])
        src, dst = cart.cart_shift(0, 1)
        assert src == (cart.rank - 1) % cart.size
        assert dst == (cart.rank + 1) % cart.size
        assert cart.topo_test() == "cart"
        # shift data around the ring via sendrecv
        buf = np.array([cart.rank], dtype=np.int64)
        out = np.zeros(1, dtype=np.int64)
        cart.sendrecv(buf, dst, 0, out, src, 0)
        assert out[0] == src
        return True
    assert all(run_ranks(4, body))


def test_cart_nonperiodic_edges():
    def body(comm):
        cart = comm.cart_create([comm.size], periods=[False])
        src, dst = cart.cart_shift(0, 1)
        if cart.rank == 0:
            assert src == PROC_NULL
        if cart.rank == cart.size - 1:
            assert dst == PROC_NULL
        # sendrecv with PROC_NULL peers must still complete
        buf = np.array([cart.rank], dtype=np.int64)
        out = np.full(1, -1, dtype=np.int64)
        cart.sendrecv(buf, dst, 0, out, src, 0)
        if src != PROC_NULL:
            assert out[0] == src
        return True
    assert all(run_ranks(4, body))


def test_cart_2d_sub():
    def body(comm):
        cart = comm.cart_create([2, 2], periods=[False, False])
        dims, periods, coords = cart.cart_get()
        assert dims == [2, 2]
        assert coords == [cart.rank // 2, cart.rank % 2]
        # rows: keep dim 1
        row = cart.cart_sub([False, True])
        assert row.size == 2
        assert row.rank == coords[1]
        # row members share coords[0]
        got = np.zeros(row.size, dtype=np.int64)
        row.allgather(np.array([coords[0]], dtype=np.int64), got, count=1)
        assert np.all(got == coords[0])
        return True
    assert all(run_ranks(4, body))


def test_graph_create_neighbors():
    def body(comm):
        # square ring graph: 0-1-2-3-0
        index = [2, 4, 6, 8]
        edges = [1, 3, 0, 2, 1, 3, 2, 0]
        g = comm.graph_create(index, edges)
        n = g.graph_neighbors()
        assert sorted(n) == sorted([(g.rank - 1) % 4, (g.rank + 1) % 4])
        assert g.topo_test() == "graph"
        return True
    assert all(run_ranks(4, body))


def test_dist_graph_adjacent_and_neighbor_alltoall():
    def body(comm):
        left = (comm.rank - 1) % comm.size
        right = (comm.rank + 1) % comm.size
        dg = comm.dist_graph_create_adjacent([left, right], [left, right])
        srcs, dsts = dg.dist_graph_neighbors()
        assert srcs == [left, right] and dsts == [left, right]
        # neighbor_alltoall: send distinct value to each side
        sbuf = np.array([dg.rank * 10 + 1, dg.rank * 10 + 2], dtype=np.int64)
        rbuf = np.zeros(2, dtype=np.int64)
        dg.neighbor_alltoall(sbuf, rbuf, count=1)
        # from left neighbor we get its block-for-right (= l*10+2);
        # from right neighbor its block-for-left (= r*10+1)
        assert rbuf[0] == left * 10 + 2, (dg.rank, rbuf)
        assert rbuf[1] == right * 10 + 1, (dg.rank, rbuf)
        return True
    assert all(run_ranks(4, body))


def test_dist_graph_general():
    def body(comm):
        # each rank declares one edge: rank -> (rank+1)%size
        dg = comm.dist_graph_create([comm.rank], [1],
                                    [(comm.rank + 1) % comm.size])
        srcs, dsts = dg.dist_graph_neighbors()
        assert dsts == [(comm.rank + 1) % comm.size]
        assert srcs == [(comm.rank - 1) % comm.size]
        return True
    assert all(run_ranks(4, body))


def test_neighbor_allgather_cart():
    def body(comm):
        cart = comm.cart_create([comm.size], periods=[True])
        sbuf = np.array([cart.rank + 100], dtype=np.int64)
        rbuf = np.zeros(2, dtype=np.int64)   # [-1, +1] neighbors
        cart.neighbor_allgather(sbuf, rbuf, count=1)
        left = (cart.rank - 1) % cart.size
        right = (cart.rank + 1) % cart.size
        assert rbuf[0] == left + 100 and rbuf[1] == right + 100, rbuf
        return True
    assert all(run_ranks(4, body))


def test_neighbor_allgather_halo_2d():
    """The stencil halo-exchange skeleton (SURVEY §5.7) on a 2x2 torus."""
    def body(comm):
        cart = comm.cart_create([2, 2], periods=[True, True])
        interior = np.full(4, float(cart.rank), dtype=np.float64)
        halo = np.zeros((4, 4), dtype=np.float64)   # 4 neighbors
        cart.neighbor_allgather(interior, halo, count=4)
        nb = cart.topo.neighbors_of(cart.rank)
        for i, r in enumerate(nb):
            assert np.all(halo[i] == float(r)), (cart.rank, i, halo)
        return True
    assert all(run_ranks(4, body))


def test_neighbor_alltoallv():
    def body(comm):
        left = (comm.rank - 1) % comm.size
        right = (comm.rank + 1) % comm.size
        dg = comm.dist_graph_create_adjacent([left, right], [left, right])
        # send 1 elem to left, 2 to right
        sbuf = np.array([dg.rank, dg.rank + 500, dg.rank + 501],
                        dtype=np.int64)
        rbuf = np.zeros(3, dtype=np.int64)
        dg.neighbor_alltoallv(sbuf, [1, 2], [0, 1], rbuf, [2, 1], [0, 2])
        # left sent me its right-block (2 elems), right its left-block (1)
        assert rbuf[0] == left + 500 and rbuf[1] == left + 501, rbuf
        assert rbuf[2] == right, rbuf
        return True
    assert all(run_ranks(4, body))


def test_cart_create_fewer_ranks():
    def body(comm):
        cart = comm.cart_create([2], periods=[False])
        if comm.rank >= 2:
            assert cart is None
            return True
        assert cart.size == 2
        return True
    assert all(run_ranks(4, body))


def test_neighbor_duplicate_peer_2rank_ring():
    """2-rank periodic cart: left == right. FIFO post-order matching
    (MPICH-compatible): recv slot k gets the peer's k-th send block."""
    def body(comm):
        cart = comm.cart_create([2], periods=[True])
        sbuf = np.array([cart.rank * 10, cart.rank * 10 + 1], dtype=np.int64)
        rbuf = np.full(2, -1, dtype=np.int64)
        cart.neighbor_alltoall(sbuf, rbuf, count=1)
        peer = 1 - cart.rank
        assert rbuf[0] == peer * 10 and rbuf[1] == peer * 10 + 1, rbuf
        return True
    assert all(run_ranks(2, body))


def test_neighbor_empty_and_oversized():
    def body(comm):
        dg = comm.dist_graph_create_adjacent([], [])
        dg.neighbor_alltoall(np.empty(0, np.int64), np.empty(0, np.int64),
                             count=1)   # no-op, must not crash
        # over-allocated recvbuf: blocks land at i*count, not spread out
        left = (comm.rank - 1) % comm.size
        right = (comm.rank + 1) % comm.size
        dg2 = comm.dist_graph_create_adjacent([left, right], [left, right])
        rbuf = np.full(8, -1, dtype=np.int64)
        dg2.neighbor_allgather(np.array([comm.rank], dtype=np.int64),
                               rbuf, count=1)
        assert rbuf[0] == left and rbuf[1] == right
        assert np.all(rbuf[2:] == -1)
        return True
    assert all(run_ranks(4, body))

"""PMPI profiling shim + debugger message-queue dump tests
(dll_mpich.c / weak-symbol PMPI analogs).

The shim interposes on the process-wide method table (like PMPI symbol
interposition interposes per process); in the thread-rank harness one
installed tool therefore sees every rank's calls.
"""

import numpy as np

from mvapich2_tpu import debugger, profile
from mvapich2_tpu.runtime.universe import run_ranks


def test_profiler_counts_and_times():
    def body(comm):
        comm.barrier()
        out = comm.allreduce(np.ones(4))
        assert out[0] == comm.size
        comm.sendrecv(np.ones(1), (comm.rank + 1) % comm.size, 0,
                      np.zeros(1), (comm.rank - 1) % comm.size, 0)
        return True

    with profile.Profiler() as prof:
        assert all(run_ranks(2, body))
    # every rank's calls are seen (process-wide interposition)
    assert prof.calls["barrier"] == 2
    assert prof.calls["allreduce"] == 2
    assert prof.calls["sendrecv"] == 2
    assert prof.seconds["allreduce"] > 0
    assert "allreduce" in prof.report()
    # uninstalled: raw table restored, no further counting
    assert all(run_ranks(2, lambda c: c.barrier() or True))
    assert prof.calls["barrier"] == 2


def test_interceptor_chain_and_pmpi():
    seen = []

    def tool(name, call, args, kwargs):
        seen.append(name)
        return call(*args[1:], **kwargs)

    def body(comm):
        comm.barrier()
        # the PMPI escape hatch bypasses the tool
        profile.pmpi("barrier")(comm)
        return True

    profile.install(tool)
    try:
        assert all(run_ranks(2, body))
    finally:
        profile.uninstall(tool)
    # 2 ranks x 1 intercepted barrier each; the pmpi path is not counted
    assert seen.count("barrier") == 2


def test_nested_tools():
    order = []

    def outer(name, call, args, kwargs):
        order.append("outer")
        return call(*args[1:], **kwargs)

    def inner(name, call, args, kwargs):
        order.append("inner")
        return call(*args[1:], **kwargs)

    profile.install(inner)
    profile.install(outer)     # outermost-last (LD_PRELOAD layering)
    try:
        assert all(run_ranks(1, lambda c: c.barrier() or True))
    finally:
        profile.uninstall()
    assert order == ["outer", "inner"]


def test_message_queue_dump():
    def body(comm):
        if comm.rank == 0:
            # leave a posted recv and let an unexpected message arrive
            req = comm.irecv(np.zeros(4), source=1, tag=77)
            comm.recv(np.zeros(1), source=1, tag=5)   # sync point
            q = debugger.dump_message_queues(comm.u)
            assert 77 in [e.tag for e in q.posted]
            assert 99 in [e.tag for e in q.unexpected]
            assert q.posted[0].comm_name == "MPI_COMM_WORLD"
            txt = q.format()
            assert "posted receives" in txt and "tag=99" in txt
            # drain both queues (go-signal first so the posted recv stays
            # queued until after the dump)
            comm.send(np.ones(1), dest=1, tag=6)
            comm.recv(np.zeros(2), source=1, tag=99)
            req.wait()
            return True
        # rank 1: unexpected msg for rank 0, sync, wait for the dump to
        # finish, then serve the posted recv
        comm.send(np.ones(2), dest=0, tag=99)
        comm.send(np.ones(1), dest=0, tag=5)
        comm.recv(np.zeros(1), source=0, tag=6)
        comm.send(np.ones(4), dest=0, tag=77)
        return True

    assert all(run_ranks(2, body))

"""Trace conformance (ISSUE 19 tentpole): bin/mv2tconform replays a
run's traces through per-protocol automata sharing invariant names
with analysis/model/*. Covered here:

  * a clean synthetic multi-rank stream and a clean real-Recorder
    script are violation-free;
  * ~16 offline seeded mutations of the synthetic stream, each caught
    by its named invariant (never silence);
  * >=10 RUNTIME seeded mutations through the real fault engine — the
    new ``trace_stamp`` site's ``skip_stamp``/``reorder`` kinds armed
    via MV2T_FAULTS against a live Recorder, each caught by name;
  * replayable counterexamples: feeding a violation's trace window
    back through fresh automata trips the same invariant;
  * tail mode (the stall watchdog's entry point) stays sound on
    truncated windows and names the first violated invariant;
  * the CLI exit-code contract (0 clean / 1 violations / 2 usage /
    3 unreadable) that perf sessions use for conformance stamps;
  * non-perturbation: the checker reads a LIVE job's ntrace segment
    read-only while the job runs, and the job still finishes clean
    (test_mpistat.py style).
"""

import json
import os
import subprocess
import sys
import threading
import time
import types

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mvapich2_tpu import faults                            # noqa: E402
from mvapich2_tpu.analysis import conform                  # noqa: E402
from mvapich2_tpu.trace.recorder import Recorder           # noqa: E402
from mvapich2_tpu.utils.config import get_config           # noqa: E402

RANKS = frozenset({0, 1, 2, 3})
OPTS = {"peer_timeout": 10.0}


def _check(events, ranks=RANKS, **kw):
    return conform.check_events(events, options=dict(OPTS), ranks=ranks,
                                **kw)


def _invariants(violations):
    return {v.invariant for v in violations}


# ---------------------------------------------------------------------------
# synthetic clean stream (4 ranks, every automaton exercised)
# ---------------------------------------------------------------------------

def _clean_stream():
    evs = []
    t = [0.0]

    def ev(r_, layer_, name_, ph="i", **args):
        t[0] += 0.001
        e = conform.Event(t[0], r_, layer_, name_, ph, args or None)
        evs.append(e)
        return e

    # two flat waves on ctx 9 (fanin all, fold on 0, fanout all)
    for seq in (1, 2):
        for r in range(4):
            ev(r, "cplane", "flat_fanin", a1=9, a2=seq)
        ev(0, "cplane", "flat_fold", a1=9, a2=seq)
        for r in range(4):
            ev(r, "cplane", "flat_fanout", a1=9, a2=seq)
    ev(0, "cplane", "coll_dispatch", a1=0, a2=0)
    # doorbell + a lease scan
    ev(0, "cplane", "bell_ring", a1=1, a2=0)
    ev(1, "cplane", "bell_wake", a1=0, a2=0)
    ev(0, "cplane", "lease_scan", a1=0, a2=0)
    # a device-shaped NBC schedule on rank 2 (deposit, 2 POLLs, close)
    ev(2, "nbc", "sched_start", sched=7, kind="dev-iallgather",
       vertices=4)
    ev(2, "nbc", "vertex_issue", sched=7, vid=0, kind=0)
    ev(2, "nbc", "vertex_complete", sched=7, vid=0)
    ev(2, "nbc", "vertex_issue", sched=7, vid=1, kind=3)
    ev(2, "device", "nbc_dev_issue", coll="iallgather", seg=0, of=2,
       n=128)
    ev(2, "device", "nbc_dev_complete", coll="iallgather", seg=0, us=5)
    ev(2, "nbc", "vertex_complete", sched=7, vid=1)
    ev(2, "nbc", "vertex_issue", sched=7, vid=2, kind=3)
    ev(2, "nbc", "vertex_complete", sched=7, vid=2)
    ev(2, "nbc", "vertex_issue", sched=7, vid=3, kind=0)
    ev(2, "nbc", "vertex_complete", sched=7, vid=3)
    ev(2, "nbc", "sched_complete", sched=7, error=False)
    # device dispatch lane on rank 3
    ev(3, "device", "dev_coll", "B", tier="vmem")
    ev(3, "device", "ici_slot", a1=0, a2=1)
    ev(3, "device", "dev_coll", "E")
    # a passive-target RMA epoch on rank 1
    ev(1, "device", "rma_lock", rank=3)
    ev(1, "device", "rma_flush", "B", rank=3, nops=1)
    ev(1, "device", "rma_put", tier="rdma", bytes=64)
    ev(1, "device", "rma_flush", "E")
    ev(1, "device", "rma_unlock", rank=3)
    # metrics rows
    ev(0, "metrics", "fp_hits", "C", value=1)
    ev(0, "metrics", "fp_hits", "C", value=5)
    ev(0, "metrics", "daemon_claims_active", "C", value=1)
    ev(0, "metrics", "daemon_claims_active", "C", value=0)
    # python mpi spans
    for r in range(4):
        ev(r, "mpi", "allreduce", "B")
        ev(r, "mpi", "allreduce", "E")
    return evs


def _tail_of(evs):
    t = max(e.ts for e in evs) + 0.001
    return t


def test_clean_stream_violation_free():
    assert _check(_clean_stream()) == []


def _drop(evs, pred, n=1):
    out, dropped = [], 0
    for e in evs:
        if dropped < n and pred(e):
            dropped += 1
            continue
        out.append(e)
    assert dropped == n, "mutation matched nothing"
    return out


def _append(evs, rank, layer, name, ph="i", **args):
    evs = list(evs)
    evs.append(conform.Event(_tail_of(evs), rank, layer, name, ph,
                             args or None))
    return evs


def _mut_drop_fanin(evs):
    return _drop(evs, lambda e: e.name == "flat_fanin" and e.rank == 0)


def _mut_mseq_regress(evs):
    return _append(evs, 1, "cplane", "flat_fanin", a1=9, a2=1)


def _mut_poison(evs):
    return _append(evs, 1, "cplane", "flat_poison", a1=-2, a2=0)


def _mut_post_poison_wave(evs):
    return _append(_mut_poison(evs), 1, "cplane", "flat_fanin",
                   a1=9, a2=3)


def _mut_drop_ring(evs):
    return _drop(evs, lambda e: e.name == "bell_ring")


def _mut_stale_lease(evs):
    return _append(evs, 0, "cplane", "lease_expire", a1=7,
                   a2=50_000_000)


def _mut_false_positive_expire(evs):
    return _append(evs, 0, "cplane", "lease_expire", a1=3,
                   a2=1_000_000)


def _mut_drop_sched_complete(evs):
    return _drop(evs, lambda e: e.name == "sched_complete")


def _mut_poll_before_deposit(evs):
    return _drop(evs, lambda e: e.name == "vertex_complete"
                 and (e.args or {}).get("vid") == 0)


def _mut_drop_vertex_issue(evs):
    return _drop(evs, lambda e: e.name == "vertex_issue"
                 and (e.args or {}).get("vid") == 1)


def _mut_poll_slot_disorder(evs):
    out = []
    for e in evs:
        if e.name == "vertex_issue" and (e.args or {}).get("vid") == 1:
            e = conform.Event(e.ts, e.rank, e.layer, e.name, e.ph,
                              dict(e.args, vid=2))
        elif e.name == "vertex_issue" and (e.args or {}).get("vid") == 2:
            e = conform.Event(e.ts, e.rank, e.layer, e.name, e.ph,
                              dict(e.args, vid=1))
        out.append(e)
    return out


def _mut_dev_complete_without_issue(evs):
    return _append(evs, 2, "device", "nbc_dev_complete",
                   coll="ireduce", seg=4, us=1)


def _mut_double_lock(evs):
    out = []
    for e in evs:
        out.append(e)
        if e.name == "rma_lock":
            out.append(conform.Event(e.ts + 1e-5, e.rank, e.layer,
                                     e.name, e.ph, dict(e.args)))
    return out


def _mut_naked_rma_op(evs):
    return _append(evs, 1, "device", "rma_get", tier="rdma", bytes=8)


def _mut_counter_regress(evs):
    return _append(evs, 0, "metrics", "fp_hits", "C", value=2)


def _mut_negative_gauge(evs):
    return _append(evs, 0, "metrics", "daemon_claims_active", "C",
                   value=-1)


def _mut_unbalanced_span(evs):
    return _append(evs, 3, "mpi", "bcast", "E")


def _mut_unknown_event(evs):
    return _append(evs, 0, "cplane", "mystery_blip", a1=0, a2=0)


OFFLINE_MUTATIONS = [
    ("drop-fanin", _mut_drop_fanin, "fanin-before-fold-before-fanout"),
    ("mseq-regress", _mut_mseq_regress, "mseq-monotone"),
    ("poison", _mut_poison, "proc-failed-poison"),
    ("post-poison-wave", _mut_post_poison_wave, "poison-sticky"),
    ("drop-bell-ring", _mut_drop_ring, "no-lost-wake"),
    ("stale-lease", _mut_stale_lease, "detect-within-deadline"),
    ("expire-departed", _mut_false_positive_expire, "no-false-positive"),
    ("drop-sched-complete", _mut_drop_sched_complete,
     "nbc-drained-at-finalize"),
    ("poll-before-deposit", _mut_poll_before_deposit,
     "nbc-deposit-before-poll"),
    ("drop-vertex-issue", _mut_drop_vertex_issue,
     "nbc-issue-before-complete"),
    ("poll-slot-disorder", _mut_poll_slot_disorder, "no-slot-collision"),
    ("dev-complete-no-issue", _mut_dev_complete_without_issue,
     "nbc-issue-before-complete"),
    ("double-lock", _mut_double_lock, "lock-exclusive"),
    ("naked-rma-op", _mut_naked_rma_op,
     "flush-completes-all-outstanding"),
    ("counter-regress", _mut_counter_regress, "counter-monotone"),
    ("negative-gauge", _mut_negative_gauge, "gauge-nonnegative"),
    ("unbalanced-span", _mut_unbalanced_span, "span-balance"),
    ("unknown-event", _mut_unknown_event, "grammar-coverage"),
]


@pytest.mark.parametrize("name,mutate,invariant",
                         OFFLINE_MUTATIONS,
                         ids=[m[0] for m in OFFLINE_MUTATIONS])
def test_offline_mutation_caught_by_named_invariant(name, mutate,
                                                    invariant):
    viols = _check(mutate(_clean_stream()))
    assert invariant in _invariants(viols), \
        f"{name}: expected {invariant}, got {_invariants(viols)}"


def test_counterexample_replays():
    """The model checkers' contract: a violation's trace window, fed
    back through fresh automata, trips the same invariant."""
    for mutate, invariant in ((_mut_mseq_regress, "mseq-monotone"),
                              (_mut_post_poison_wave, "poison-sticky")):
        viols = [v for v in _check(mutate(_clean_stream()))
                 if v.invariant == invariant]
        assert viols and viols[0].trace
        assert conform.replay(viols[0], options=dict(OPTS)), \
            f"replay of {invariant} window did not reproduce"


# ---------------------------------------------------------------------------
# runtime seeded mutations: the trace_stamp fault site through a REAL
# Recorder — MV2T_FAULTS skip_stamp/reorder kinds, each caught by name
# ---------------------------------------------------------------------------

_SCRIPT = [
    ("cplane", "flat_fanin", "i", dict(a1=5, a2=1)),        # 1
    ("cplane", "flat_fold", "i", dict(a1=5, a2=1)),         # 2
    ("cplane", "flat_fanout", "i", dict(a1=5, a2=1)),       # 3
    ("cplane", "bell_ring", "i", dict(a1=1, a2=0)),         # 4
    ("cplane", "bell_wake", "i", dict(a1=0, a2=0)),         # 5
    ("nbc", "sched_start", "i",
     dict(sched=7, kind="dev-iallreduce", vertices=3)),     # 6
    ("nbc", "vertex_issue", "i", dict(sched=7, vid=0, kind=0)),   # 7
    ("nbc", "vertex_complete", "i", dict(sched=7, vid=0)),        # 8
    ("nbc", "vertex_issue", "i", dict(sched=7, vid=1, kind=3)),   # 9
    ("nbc", "vertex_issue", "i", dict(sched=7, vid=2, kind=3)),   # 10
    ("nbc", "vertex_complete", "i", dict(sched=7, vid=1)),        # 11
    ("nbc", "vertex_complete", "i", dict(sched=7, vid=2)),        # 12
    ("nbc", "sched_complete", "i", dict(sched=7, error=False)),   # 13
    ("device", "rma_lock", "i", dict(rank=1)),              # 14
    ("device", "rma_flush", "B", dict(rank=1, nops=1)),     # 15
    ("device", "rma_put", "i", dict(tier="rdma", bytes=64)),  # 16
    ("device", "rma_flush", "E", dict()),                   # 17
    ("device", "rma_unlock", "i", dict(rank=1)),            # 18
    ("mpi", "allreduce", "B", dict()),                      # 19
    ("mpi", "allreduce", "E", dict()),                      # 20
]


def _run_script(fault_spec=None):
    """Drive the canonical script through a real Recorder, optionally
    with a trace_stamp fault armed, and conformance-check the dump."""
    cfg = get_config()
    old = cfg.get("FAULTS", "")
    try:
        cfg.set("FAULTS", fault_spec or "")
        if fault_spec:
            assert faults.configure(0) == 1
        else:
            faults.deconfigure()
        rec = Recorder(0, 4096)
        for layer, name, ph, args in _SCRIPT:
            rec.record(layer, name, ph, **args)
        evs, _trunc = conform._dump_to_events(rec.snapshot())
        return conform.check_events(evs, options=dict(OPTS),
                                    ranks=frozenset({0}))
    finally:
        cfg.set("FAULTS", old)
        faults.deconfigure()


def test_runtime_clean_script_violation_free():
    assert _run_script() == []


RUNTIME_MUTATIONS = [
    ("skip-fanin", "trace_stamp:skip_stamp:0:1",
     "fanin-before-fold-before-fanout"),
    ("skip-bell-ring", "trace_stamp:skip_stamp:0:4", "no-lost-wake"),
    ("skip-vertex-issue-call", "trace_stamp:skip_stamp:0:7",
     "nbc-issue-before-complete"),
    ("skip-deposit-complete", "trace_stamp:skip_stamp:0:8",
     "nbc-deposit-before-poll"),
    ("skip-vertex-issue-poll", "trace_stamp:skip_stamp:0:9",
     "nbc-issue-before-complete"),
    ("skip-sched-complete", "trace_stamp:skip_stamp:0:13",
     "nbc-drained-at-finalize"),
    ("skip-rma-lock", "trace_stamp:skip_stamp:0:14", "lock-exclusive"),
    ("skip-flush-begin", "trace_stamp:skip_stamp:0:15",
     "flush-completes-all-outstanding"),
    ("skip-mpi-begin", "trace_stamp:skip_stamp:0:19", "span-balance"),
    ("reorder-fold-before-fanin", "trace_stamp:reorder:0:2",
     "fanin-before-fold-before-fanout"),
    ("reorder-wake-before-ring", "trace_stamp:reorder:0:5",
     "no-lost-wake"),
    ("reorder-poll-slots", "trace_stamp:reorder:0:10",
     "no-slot-collision"),
    ("reorder-op-outside-flush", "trace_stamp:reorder:0:16",
     "flush-completes-all-outstanding"),
]


@pytest.mark.parametrize("name,spec,invariant", RUNTIME_MUTATIONS,
                         ids=[m[0] for m in RUNTIME_MUTATIONS])
def test_runtime_fault_caught_by_named_invariant(name, spec, invariant):
    viols = _run_script(spec)
    assert invariant in _invariants(viols), \
        f"{name} ({spec}): expected {invariant}, " \
        f"got {_invariants(viols)}"


# ---------------------------------------------------------------------------
# tail mode — the stall watchdog's window
# ---------------------------------------------------------------------------

def test_tail_mode_names_poison():
    rows = [(1.0, "cplane", "flat_fanin", "i", {"a1": 5, "a2": 1}),
            (2.0, "cplane", "flat_poison", "i", {"a1": -2, "a2": 0}),
            (3.0, "cplane", "flat_fanout", "i", {"a1": 5, "a2": 2})]
    viols = conform.check_tail(1, rows, options=dict(OPTS))
    assert "proc-failed-poison" in _invariants(viols)
    assert "poison-sticky" in _invariants(viols)


def test_tail_mode_suppresses_truncation_artifacts():
    """A window that starts mid-run: E-without-B, an sched with no
    start, a wake whose ring predates the window — none may fire."""
    rows = [(1.0, "mpi", "allreduce", "E", None),
            (2.0, "nbc", "vertex_complete", "i", {"sched": 3, "vid": 1}),
            (3.0, "cplane", "bell_wake", "i", {"a1": 0, "a2": 0}),
            (4.0, "device", "rma_unlock", "i", {"rank": 2}),
            (5.0, "nbc", "sched_start", "i",
             {"sched": 9, "kind": "net-ibcast", "vertices": 2})]
    assert conform.check_tail(0, rows, options=dict(OPTS)) == []


def test_watchdog_report_names_first_violated_invariant():
    """The watchdog's hang report runs conformance over the trace tail
    and names the first violated invariant."""
    from mvapich2_tpu.trace import watchdog
    rec = Recorder(0, 256)
    rec.record("cplane", "flat_fanin", a1=5, a2=1)
    rec.record("cplane", "flat_poison", a1=-2, a2=0)
    eng = types.SimpleNamespace(
        rank=0, mutex=threading.Lock(), outstanding={}, universe=None,
        nbc=None, _lockcheck=None, _stall_limit=5.0, tracer=rec)
    report = watchdog.build_report(eng)
    assert "trace-tail conformance" in report
    assert "flat-wave/proc-failed-poison" in report


def test_watchdog_report_clean_tail_says_liveness():
    from mvapich2_tpu.trace import watchdog
    rec = Recorder(0, 256)
    rec.record("cplane", "flat_fanin", a1=5, a2=1)
    eng = types.SimpleNamespace(
        rank=0, mutex=threading.Lock(), outstanding={}, universe=None,
        nbc=None, _lockcheck=None, _stall_limit=5.0, tracer=rec)
    report = watchdog.build_report(eng)
    assert "no invariant violated" in report


# ---------------------------------------------------------------------------
# CLI exit-code contract
# ---------------------------------------------------------------------------

def _write_dump(tmp_path, events, rank=0):
    path = tmp_path / f"trace-r{rank}.json"
    path.write_text(json.dumps({
        "rank": rank, "clock": "monotonic", "capacity": 4096,
        "events": [[e.ts, e.layer, e.name, e.ph, e.args]
                   for e in events if e.rank == rank]}))
    return str(path)


def test_cli_exit_codes(tmp_path, capsys):
    clean = [e for e in _clean_stream() if e.rank == 0
             and e.layer != "metrics"]
    _write_dump(tmp_path, clean)
    assert conform.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "0 violation(s)" in out

    bad = _append(clean, 0, "cplane", "flat_poison", a1=-2, a2=0)
    bad_dir = tmp_path / "bad"
    bad_dir.mkdir()
    _write_dump(bad_dir, bad)
    assert conform.main([str(bad_dir)]) == 1
    out = capsys.readouterr().out
    assert "proc-failed-poison" in out

    assert conform.main([str(tmp_path / "nope.txt")]) == 2
    empty = tmp_path / "empty-dir"
    empty.mkdir()
    assert conform.main([str(empty)]) == 3
    capsys.readouterr()


def test_cli_json_output(tmp_path, capsys):
    bad = _append([e for e in _clean_stream() if e.rank == 0
                   and e.layer != "metrics"],
                  0, "cplane", "flat_poison", a1=-2, a2=0)
    _write_dump(tmp_path, bad)
    assert conform.main([str(tmp_path), "--json"]) == 1
    parsed = json.loads(capsys.readouterr().out)
    assert parsed and parsed[0]["invariant"] == "proc-failed-poison"
    assert parsed[0]["trace"]


# ---------------------------------------------------------------------------
# the event-coverage doctor <-> checker grammar agreement
# ---------------------------------------------------------------------------

def test_nbc_grammar_imported_from_model():
    """The NBC automaton's grammar IS the model's TRACE_EVENTS table —
    the no-drift coupling the tentpole requires."""
    from mvapich2_tpu.analysis.model import nbc as nbc_model
    got = set(conform.NbcAutomaton.grammar)
    want = {(layer, n) for layer, names
            in nbc_model.TRACE_EVENTS.items() for n in names}
    assert got == want


def test_native_events_covered_by_grammar():
    from mvapich2_tpu.trace import native
    for name, _region in native._NT_EVENTS:
        assert conform.grammar_covers("cplane", name), name


# ---------------------------------------------------------------------------
# non-perturbation: conformance over a LIVE job's segments, read-only
# ---------------------------------------------------------------------------

def test_conform_does_not_perturb_live_job():
    """test_mpistat.py style: attach the conformance checker to a
    running job's ntrace segment (read-only) while it is mid-collective
    loop; the job must still finish with "No Errors". Tail mode, since
    the window is a partial run by construction."""
    env = dict(os.environ)
    env["MV2T_TEST_STAT_SECONDS"] = "8"
    env["MV2T_NTRACE"] = "1"         # native ring on, recorder off
    env.pop("MV2T_TRACE", None)
    target = os.path.join(REPO, "tests", "progs",
                          "mpistat_target_prog.py")
    job = subprocess.Popen(
        [sys.executable, "-m", "mvapich2_tpu.run", "-np", "2",
         sys.executable, target],
        cwd=REPO, env=env, stdout=subprocess.PIPE, text=True)
    try:
        seg = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            line = job.stdout.readline()
            if line.startswith("SEG "):
                seg = line.split()[1]
                break
        assert seg, "target job never printed its segment stem"
        time.sleep(2.0)              # let some collectives run
        nt = seg + ".ntrace"
        assert os.path.exists(nt)
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bin", "mv2tconform"),
             nt, "--tail"],
            capture_output=True, text=True, timeout=60)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "0 violation(s)" in r.stdout
        rest = job.stdout.read()
        assert job.wait(timeout=120) == 0
        assert "No Errors" in rest
    finally:
        if job.poll() is None:
            job.kill()


# ---------------------------------------------------------------------------
# the chaos kill class: a seeded MV2T_FAULTS crash is NEVER silence
# ---------------------------------------------------------------------------

def test_seeded_kill_yields_poison_violation_class(tmp_path):
    """A mid-collective kill (native flat_fold crash site) must show up
    in conformance as the PROC_FAILED/poison violation class on the
    survivors' traces — a failure run can never be certified clean."""
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               MV2T_FAULTS="flat_fold@0:crash:1:5",
               MV2T_CHAOS_PHASES="flat",
               MV2T_PEER_TIMEOUT="3.0",
               MV2T_FT_WATCHER="0",
               MPIEXEC_ALLOW_FAULT="1",
               MV2T_TRACE="1",
               MV2T_TRACE_DIR=str(tmp_path))
    prog = os.path.join(REPO, "tests", "progs", "chaos_prog.py")
    r = subprocess.run(
        [sys.executable, "-m", "mvapich2_tpu.run", "-np", "4",
         sys.executable, prog],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "No Errors" in r.stdout
    assert list(tmp_path.glob("trace-r*.json")), "survivors dumped no traces"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "mv2tconform"),
         str(tmp_path), "--json"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 1, \
        f"kill run certified clean (exit {r.returncode}):\n{r.stdout}"
    parsed = json.loads(r.stdout)
    assert any(v["invariant"] == "proc-failed-poison" for v in parsed), \
        [v["invariant"] for v in parsed]

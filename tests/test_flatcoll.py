"""Flat-slot collective tier correctness (ISSUE 5 tentpole coverage).

Two sweeps of the same surface — allreduce/reduce/bcast/barrier across
ops x dtypes x sizes straddling every protocol boundary (flat payload
max 4 KiB, the eager size, FP_COLL_MAX), over world + dup'd + split +
context-reused comms:

- flatcoll_test.c through the unmodified C ABI (fastpath.c dispatch),
- flatpy_sweep_prog.py through the python API (coll/flatcoll.py),

both against the ONE cp_flat_* engine in cplane.cpp. np in {2, 3, 4}
runs tier-1; np=8 (the tier's nslots ceiling) rides the slow lane.
"""

import os
import shutil
import subprocess
import sys
import tempfile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MPICC = os.path.join(REPO, "bin", "mpicc")

pytestmark = pytest.mark.skipif(
    shutil.which("gcc") is None or shutil.which("python3-config") is None,
    reason="no C toolchain")


def _mpirun(np_, *cmd, timeout=420):
    r = subprocess.run([sys.executable, "-m", "mvapich2_tpu.run", "-np",
                        str(np_), *cmd], cwd=REPO, capture_output=True,
                       text=True, timeout=timeout)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout, f"stdout={r.stdout}\nstderr={r.stderr}"


@pytest.fixture(scope="module")
def flat_c_prog():
    out = os.path.join(tempfile.mkdtemp(), "flatcoll_test")
    src = os.path.join(REPO, "tests", "progs", "flatcoll_test.c")
    r = subprocess.run([MPICC, src, "-o", out], capture_output=True,
                       text=True, timeout=180)
    assert r.returncode == 0, f"mpicc failed:\n{r.stdout}\n{r.stderr}"
    return out


@pytest.mark.parametrize("np_", [2, 3, 4])
def test_flat_sweep_cabi(flat_c_prog, np_):
    _mpirun(np_, flat_c_prog)


@pytest.mark.slow
def test_flat_sweep_cabi_np8(flat_c_prog):
    _mpirun(8, flat_c_prog, timeout=600)


@pytest.mark.parametrize("np_", [2, 4])
def test_flat_sweep_python(np_):
    prog = os.path.join(REPO, "tests", "progs", "flatpy_sweep_prog.py")
    _mpirun(np_, sys.executable, prog)


@pytest.mark.slow
def test_flat_sweep_python_np3(py=3):
    prog = os.path.join(REPO, "tests", "progs", "flatpy_sweep_prog.py")
    _mpirun(3, sys.executable, prog)

"""HBM-streaming ICI collective engine (ops/pallas_ici) — interpret-mode
correctness sweep on the 8-device virtual CPU mesh.

The chunked remote-DMA kernels must bit-agree with the XLA lowering for
every op x dtype x chunk-boundary shape (integer-valued data makes
float sums order-independent, so "bit-agreement" is exact, not rtol);
the double-buffer schedule must be invariant under pipeline depth; the
tier dispatcher must route by the measured boundaries and count every
XLA fallback.
"""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from mvapich2_tpu import mpit  # noqa: E402
from mvapich2_tpu.ops import pallas_ici, pallas_ring  # noqa: E402
from mvapich2_tpu.parallel import MeshComm, make_mesh  # noqa: E402
from mvapich2_tpu.utils.config import get_config  # noqa: E402

NP = 8


@pytest.fixture(scope="module")
def comm8():
    return MeshComm(make_mesh((NP,), ("x",)))


def _reload(**env):
    for k, v in env.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    get_config().reload()


@pytest.fixture(autouse=True)
def _clean_env():
    yield
    _reload(MV2T_ICI_INTERPRET=None, MV2T_DEV_TIER_VMEM_MAX=None,
            MV2T_DEV_TIER_XLA_MIN=None, MV2T_ICI_CHUNK_BYTES=None,
            MV2T_ICI_PIPELINE_DEPTH=None, MV2T_ICI_BIDIR=None)


def _expect(xv, op):
    blocks = np.asarray(xv, np.float64).reshape(NP, -1)
    return {"sum": blocks.sum(0), "max": blocks.max(0),
            "min": blocks.min(0), "prod": blocks.prod(0)}[op]


def _run_ar(comm8, xv, op="sum", **kw):
    out = comm8.run(lambda s: pallas_ici.hbm_ring_all_reduce(
        s, "x", NP, op=op, interpret=True, **kw), jnp.asarray(xv))
    return np.asarray(out).reshape(NP, -1)


# ---------------------------------------------------------------------------
# chunk-boundary shapes (shard x chunk remainders, degenerate 1-chunk)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("shard,chunk_bytes", [
    (8, 16),          # shard divides p, chunks divide the block exactly
    (13, 16),         # shard % p != 0: identity-padded tail
    (37, 64),         # non-divisible block/chunk remainder (last short)
    (5, 1 << 20),     # 1-chunk degenerate: chunk covers the whole block
])
def test_allreduce_chunk_boundaries_bitwise(comm8, shard, chunk_bytes):
    xv = (np.arange(NP * shard) % 7).astype(np.float32)
    got = _run_ar(comm8, xv, chunk_bytes=chunk_bytes)
    exp = _expect(xv, "sum")
    for row in got:
        np.testing.assert_array_equal(row, exp)


@pytest.mark.parametrize("op,dtype", [
    ("max", np.int32),
    ("min", np.int32),
    ("prod", np.float32),
])
def test_allreduce_ops_bitwise(comm8, op, dtype):
    n = NP * 16
    xv = ((np.arange(n) % 2 + 1) if op == "prod"
          else (np.arange(n) % 11 - 5)).astype(dtype)
    got = _run_ar(comm8, xv, op=op, chunk_bytes=32)
    exp = _expect(xv, op).astype(dtype)
    for row in got:
        np.testing.assert_array_equal(row.astype(dtype), exp)


def test_allreduce_bf16_bitwise(comm8):
    # integer values small enough that every partial is bf16-exact
    xv = (np.arange(NP * 8) % 5).astype(np.float32)
    out = comm8.run(lambda s: pallas_ici.hbm_ring_all_reduce(
        s, "x", NP, interpret=True, chunk_bytes=16),
        jnp.asarray(xv, dtype=jnp.bfloat16))
    got = np.asarray(out.astype(jnp.float32)).reshape(NP, -1)
    exp = _expect(xv, "sum")
    for row in got:
        np.testing.assert_array_equal(row, exp)


def test_allreduce_agrees_with_xla_lowering(comm8):
    """The acceptance identity: chunked kernel == lax.psum, bitwise
    (integer-valued f32 makes the sum order-free)."""
    xv = (np.arange(NP * 24) % 13).astype(np.float32)
    got = _run_ar(comm8, xv, chunk_bytes=32)
    from mvapich2_tpu import ops
    ref = comm8.run(lambda s: ops.allreduce(s, "x"), jnp.asarray(xv))
    np.testing.assert_array_equal(got,
                                  np.asarray(ref).reshape(NP, -1))


def test_allreduce_unidirectional(comm8):
    xv = (np.arange(NP * 12) % 9).astype(np.float32)
    got = _run_ar(comm8, xv, chunk_bytes=16, bidirectional=False)
    exp = _expect(xv, "sum")
    for row in got:
        np.testing.assert_array_equal(row, exp)


# ---------------------------------------------------------------------------
# pipelining depth (the double-buffer schedule)
# ---------------------------------------------------------------------------

def test_pipeline_depth_invariance(comm8):
    """Deeper pipelines reorder DMA issue, never results."""
    xv = (np.arange(NP * 37) % 7).astype(np.float32)
    exp = _expect(xv, "sum")
    for depth in (3, 4):
        got = _run_ar(comm8, xv, chunk_bytes=64, depth=depth)
        for row in got:
            np.testing.assert_array_equal(row, exp)


def test_chunk_schedule_unit():
    """Static schedule invariants: chunks tile the span exactly, the
    remainder rides the last chunk, and the global-counter slot
    sequence never lands a write in a slot still inside the
    outstanding window (the credit-correctness precondition)."""
    for lo, hi, chunk in [(0, 64, 16), (0, 37, 16), (19, 37, 8),
                          (0, 5, 1 << 20)]:
        cl = pallas_ici._chunks(lo, hi, chunk)
        assert cl[0][0] == lo
        assert sum(sz for _, sz in cl) == hi - lo
        offs = [off for off, _ in cl]
        assert offs == sorted(offs)
        assert all(sz == chunk for _, sz in cl[:-1])
    for depth in (2, 3, 4):
        for total in (1, 3, 7, 8):
            slots = [k % depth for k in range(total)]
            for k in range(total):
                window = slots[k + 1:k + depth]   # outstanding writes
                if k + depth < total:
                    assert slots[k + depth] not in window
                    assert slots[k + depth] == slots[k]


def test_scratch_scales_with_depth_and_chunk():
    a = pallas_ici._scratch_shapes(2, 2, 64, jnp.float32)
    b = pallas_ici._scratch_shapes(2, 4, 64, jnp.float32)
    # three data buffers lead; VMEM bytes double with depth
    assert a[0].shape == (2, 2, 64) and b[0].shape == (2, 4, 64)
    assert len(a) == len(b)


# ---------------------------------------------------------------------------
# all-gather + the pt2pt lane
# ---------------------------------------------------------------------------

def test_hbm_all_gather_bitwise(comm8):
    xv = np.arange(NP * 13, dtype=np.int32)
    out = comm8.run(lambda s: pallas_ici.hbm_ring_all_gather(
        s, "x", NP, chunk_bytes=16, interpret=True), jnp.asarray(xv),
        out_specs=P("x"))
    got = np.asarray(out).reshape(NP, NP * 13)
    for row in got:
        np.testing.assert_array_equal(row, xv)


def test_remote_sendrecv_exchange(comm8):
    xv = np.arange(NP * 4, dtype=np.float32)
    out = comm8.run(lambda s: pallas_ici.remote_sendrecv(
        s, "x", NP, src=2, dst=5, interpret=True), jnp.asarray(xv),
        out_specs=P("x"))
    got = np.asarray(out).reshape(NP, 4)
    exp = xv.reshape(NP, 4).copy()
    exp[[2, 5]] = exp[[5, 2]]        # src<->dst swap; others identity
    np.testing.assert_array_equal(got, exp)


# ---------------------------------------------------------------------------
# tier dispatch + fallback observability
# ---------------------------------------------------------------------------

def test_planned_tier_reasons():
    _reload(MV2T_ICI_INTERPRET="1", MV2T_DEV_TIER_VMEM_MAX="64",
            MV2T_DEV_TIER_XLA_MIN="4096")
    assert pallas_ici.planned_tier("allreduce", 64, np.float32,
                                   "sum") == ("vmem", None)
    assert pallas_ici.planned_tier("allreduce", 100, np.float32,
                                   "sum") == ("hbm", None)
    assert pallas_ici.planned_tier("allreduce", 8192, np.float32,
                                   "sum") == ("xla", "size")
    assert pallas_ici.planned_tier("allreduce", 100, np.float32,
                                   "land") == ("xla", "dtype")
    assert pallas_ici.planned_tier("allreduce", 100, np.complex64,
                                   "sum") == ("xla", "dtype")
    assert pallas_ici.planned_tier("allreduce", 0, np.float32,
                                   "sum") == ("xla", "shape")
    _reload(MV2T_ICI_INTERPRET=None)
    if jax.devices()[0].platform != "tpu":
        assert pallas_ici.planned_tier(
            "allreduce", 100, np.float32, "sum") == ("xla", "platform")


def test_default_tier_edges_cover_the_old_cliff():
    """The acceptance bound: with compiled-in defaults (no profile
    override), buffers past the 4 MiB VMEM cap plan the HBM-streaming
    tier — never a silent XLA fallback."""
    from mvapich2_tpu.coll import tuning
    _reload(MV2T_DEV_TIER_VMEM_MAX=None, MV2T_DEV_TIER_XLA_MIN=None)
    saved = dict(tuning._DEVICE_CROSSOVERS)
    tuning._DEVICE_CROSSOVERS.clear()
    try:
        assert tuning.device_tier("allreduce", 4 * 1024 * 1024) == "vmem"
        assert tuning.device_tier("allreduce", 4 * 1024 * 1024 + 1) \
            == "hbm"
        assert tuning.device_tier("allreduce", 1 << 30) == "hbm"
        # a measured profile re-enters XLA above its crossover
        tuning._DEVICE_CROSSOVERS["dev_tier_xla_min"] = 1 << 26
        assert tuning.device_tier("allreduce", 1 << 27) == "xla"
        # an explicit cvar outranks the measurement
        _reload(MV2T_DEV_TIER_XLA_MIN="-1")
        assert tuning.device_tier("allreduce", 1 << 27) == "hbm"
    finally:
        tuning._DEVICE_CROSSOVERS.clear()
        tuning._DEVICE_CROSSOVERS.update(saved)


def test_dispatcher_routes_hbm(comm8):
    _reload(MV2T_ICI_INTERPRET="1", MV2T_DEV_TIER_VMEM_MAX="16",
            MV2T_ICI_CHUNK_BYTES="32")
    xv = (np.arange(NP * 16) % 7).astype(np.float32)   # shard 64 B > 16
    out = comm8.run(lambda s: pallas_ici.ici_all_reduce(s, "x", NP),
                    jnp.asarray(xv))
    got = np.asarray(out).reshape(NP, -1)
    exp = _expect(xv, "sum")
    for row in got:
        np.testing.assert_array_equal(row, exp)


def test_vmem_reject_counts_fallback_pvar(comm8):
    """The once-silent pallas_ring rejection now bumps the pvar family
    (per traced shape)."""
    before = mpit.pvar("dev_coll_fallback_shape").read()
    xv = np.arange(NP * 5, dtype=np.float32)   # shard 5 % 8 != 0
    out = comm8.run(lambda s: pallas_ring.ring_all_reduce(s, "x", NP),
                    jnp.asarray(xv))
    exp = _expect(xv, "sum")
    np.testing.assert_array_equal(np.asarray(out).reshape(NP, -1)[0], exp)
    assert mpit.pvar("dev_coll_fallback_shape").read() >= before + 1

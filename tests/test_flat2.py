"""Hierarchical flat tier + multicast bcast correctness (ISSUE 11).

The np > 8 sibling of test_flatcoll.py, over the SAME one cp_flat2_*
engine in cplane.cpp from both ABIs:

- flat2_sweep_prog.py through the python API (coll/flatcoll.py):
  allreduce/reduce/bcast/barrier x ops x dtypes x group-boundary
  sizes/roots, pipelined mcast streams, dup/split/ctx-reuse, and a
  tier-usage assertion (fp_coll_flat2 moved);
- flatcoll_test.c through the unmodified C ABI (fastpath.c
  fpc_flat2_next dispatch);
- a mid-wave LEADER-crash chaos job (native flat_fold site fires
  inside cp_flat2_*): survivors must lease-detect, poison the flat2
  region, unwind with MPIX_ERR_PROC_FAILED, and recover on a shrunken
  comm whose tier/lane re-derive from the surviving membership
  (extends the PR 6 _rekey_flat path to tier 2).

np in {9, 12(k=4), 16} runs tier-1; np in {24, 64} and the C-ABI
np=16 sweep ride the slow lane.
"""

import os
import re
import shutil
import subprocess
import sys
import tempfile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MPICC = os.path.join(REPO, "bin", "mpicc")
PY_PROG = os.path.join(REPO, "tests", "progs", "flat2_sweep_prog.py")
CHAOS_PROG = os.path.join(REPO, "tests", "progs", "chaos_prog.py")

pytestmark = pytest.mark.skipif(
    shutil.which("gcc") is None or shutil.which("python3-config") is None,
    reason="no C toolchain")


def _mpirun(np_, *cmd, timeout=420, env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    if env_extra:
        env.update(env_extra)
    r = subprocess.run([sys.executable, "-m", "mvapich2_tpu.run", "-np",
                        str(np_), *cmd], cwd=REPO, capture_output=True,
                       text=True, timeout=timeout, env=env)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout, \
        f"stdout={r.stdout}\nstderr={r.stderr}"
    return r


@pytest.fixture(scope="module")
def flat_c_prog():
    out = os.path.join(tempfile.mkdtemp(), "flatcoll_test")
    src = os.path.join(REPO, "tests", "progs", "flatcoll_test.c")
    r = subprocess.run([MPICC, src, "-o", out], capture_output=True,
                       text=True, timeout=180)
    assert r.returncode == 0, f"mpicc failed:\n{r.stdout}\n{r.stderr}"
    return out


# -- python-API sweeps ----------------------------------------------------

@pytest.mark.parametrize("np_", [9, 16])
def test_flat2_sweep_python(np_):
    _mpirun(np_, sys.executable, PY_PROG)


def test_flat2_sweep_python_group_width_4():
    """MV2T_FLAT2_GROUP=4: 12 ranks = 3 groups of 4 — the leaders-of-k
    geometry at a non-default k, including a k that does not divide
    np at the split halves (6 = flat tier)."""
    _mpirun(12, sys.executable, PY_PROG,
            env_extra={"MV2T_FLAT2_GROUP": "4"})


@pytest.mark.slow
@pytest.mark.parametrize("np_", [24, 64])
def test_flat2_sweep_python_wide(np_):
    _mpirun(np_, sys.executable, PY_PROG, timeout=900)


# -- C-ABI sweeps (flatcoll_test.c is np-generic; at np > 8 the world
#    comm and its dup ride the flat2 tier, split halves the flat tier) --

def test_flat2_sweep_cabi_np9(flat_c_prog):
    _mpirun(9, flat_c_prog, timeout=600)


@pytest.mark.slow
def test_flat2_sweep_cabi_np16(flat_c_prog):
    _mpirun(16, flat_c_prog, timeout=900)


# -- kill switch ----------------------------------------------------------

def test_flat2_kill_switch_falls_back_to_sched():
    """MV2T_FLAT2=0 stands the tier down unanimously at attach; the
    sweep (minus its tier-usage assertion, which gates on cp_flat2_ok)
    must pass on the scheduled tier."""
    _mpirun(9, sys.executable, PY_PROG,
            env_extra={"MV2T_FLAT2": "0"})


# -- leader-crash chaos (extends PR 6 _rekey_flat to tier 2) -------------

def _chaos(np_, faults_spec, timeout=240):
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               MV2T_FAULTS=faults_spec,
               MV2T_CHAOS_PHASES="flat",
               MV2T_PEER_TIMEOUT="2.0",
               MV2T_FT_WATCHER="0",
               MPIEXEC_ALLOW_FAULT="1")
    r = subprocess.run(
        [sys.executable, "-m", "mvapich2_tpu.run", "-np", str(np_),
         sys.executable, CHAOS_PROG],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=timeout)
    assert r.returncode == 0, \
        f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "No Errors" in r.stdout, \
        f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    pat = re.compile(r"chaos: rank=(\d+) phase=(\S+) err=(\S+) "
                     r"detect_s=([\d.]+) shrunk=(\d+)")
    return [m.groups() for m in pat.finditer(r.stdout)]


def test_flat2_leader_crash_rekeys_and_recovers():
    """Rank 0 — the ROOT LEADER of the two-level wave (group 0's
    leader and the leaders-exchange folder) — crash-selfs inside a
    flat2 wave via the native flat_fold site. Survivors' flat2 waits
    must lease-detect within the deadline, sticky-poison the region,
    unwind with MPIX_ERR_PROC_FAILED (err=75), and recover on the
    shrunken np=8 comm — which re-keys onto the FLAT tier with a lane
    re-derived from the surviving membership."""
    lines = _chaos(9, "flat_fold@0:crash:1:5")
    saw = False
    for _rank, phase, err, detect_s, shrunk in lines:
        if err != "None":
            saw = True
            assert err == "75", lines         # MPIX_ERR_PROC_FAILED
            assert phase == "flat"
            assert float(detect_s) < 24.0, lines   # 2x timeout + slack
            assert shrunk == "8", lines
    assert saw, f"no survivor saw the leader failure: {lines}"


@pytest.mark.chaos
def test_flat2_group_leader_crash_np16():
    """A NON-root group leader (rank 8 = group 1's leader at k=8) dies
    mid-wave: the root leader's exchange wait and group 1's members'
    fan-out waits both unwind; survivors shrink to 15 and stay on the
    flat2 tier (15 > 8) with a fresh region."""
    lines = _chaos(16, "flat_fold@8:crash:1:5", timeout=420)
    assert any(err == "75" and shrunk == "15"
               for _r, _p, err, _d, shrunk in lines), lines


@pytest.mark.chaos
def test_flat2_member_crash_np16():
    """A plain member (rank 5, mid-group) dies mid-wave; its group
    leader's fold wait unwinds and containment proceeds as above."""
    lines = _chaos(16, "flat_fold@5:crash:1:5", timeout=420)
    assert any(err == "75" and shrunk == "15"
               for _r, _p, err, _d, shrunk in lines), lines

"""Dynamic-process tests: spawn, ports, connect/accept, nameserv
(MPICH test/mpi/spawn analogs)."""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from mvapich2_tpu.runtime import nameserv as ns
from mvapich2_tpu.runtime import spawn as sp
from mvapich2_tpu.runtime import universe as uni
from mvapich2_tpu.runtime.universe import run_ranks

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_thread_spawn_intercomm():
    child_box = {}

    def child_main(cw):
        u = uni.current_universe()
        parent = sp.get_parent(u)
        assert parent is not None and parent.is_inter
        out = parent.allreduce(np.array([10 + cw.rank], dtype=np.int64))
        child_box[cw.rank] = int(out[0])
        parent.barrier()

    def body(world):
        inter, errs = sp.comm_spawn(world, child_main, maxprocs=2, root=0)
        assert all(e == 0 for e in errs)
        assert inter.remote_size == 2
        out = inter.allreduce(np.array([world.rank + 1], dtype=np.int64))
        assert int(out[0]) == 10 + 11          # children's contributions
        inter.barrier()
        return True

    assert all(run_ranks(2, body))
    # children saw the parents' sum (1 + 2)
    assert child_box == {0: 3, 1: 3}


def test_thread_spawn_merge():
    def child_main(cw):
        u = uni.current_universe()
        parent = sp.get_parent(u)
        merged = parent.merge(high=True)
        out = merged.allreduce(np.array([1], dtype=np.int64))
        assert int(out[0]) == merged.size

    def body(world):
        inter, _ = sp.comm_spawn(world, child_main, maxprocs=3, root=0)
        merged = inter.merge(high=False)
        assert merged.size == world.size + 3
        # low (parent) side first
        assert merged.rank == world.rank
        out = merged.allreduce(np.array([1], dtype=np.int64))
        assert int(out[0]) == merged.size
        return True

    assert all(run_ranks(2, body))


def test_ports_connect_accept():
    def body(world):
        half = world.size // 2
        server = world.rank < half
        local = world.split(0 if server else 1, world.rank)
        if server:
            if local.rank == 0:
                port = sp.open_port(world.u)
                ns.publish_name(world.u, "svc-test", port)
            else:
                port = "mv2t-port:0:0"   # only the root's port matters
            inter = sp.comm_accept(port, local, 0)
        else:
            port = ns_wait("svc-test")
            inter = sp.comm_connect(port, local, 0)
        assert inter.remote_size == half
        out = inter.allreduce(np.array([world.rank], dtype=np.int64))
        remote = range(half, world.size) if server else range(half)
        assert int(out[0]) == sum(remote)
        inter.disconnect()
        return True

    def ns_wait(name):
        u = uni.current_universe()
        for _ in range(500):
            try:
                return ns.lookup_name(u, name)
            except Exception:
                time.sleep(0.01)
        raise TimeoutError(name)

    assert all(run_ranks(4, body))


def test_nameserv_errors():
    def body(world):
        if world.rank == 0:
            ns.publish_name(world.u, "ephemeral", "mv2t-port:0:1")
            assert ns.lookup_name(world.u, "ephemeral") == "mv2t-port:0:1"
            ns.unpublish_name(world.u, "ephemeral")
            with pytest.raises(Exception):
                ns.lookup_name(world.u, "ephemeral")
            with pytest.raises(Exception):
                ns.unpublish_name(world.u, "ephemeral")
        return True

    assert all(run_ranks(1, body))


@pytest.mark.slow
def test_process_spawn():
    """End-to-end: launched parent job spawns child processes which join
    via the KVS and talk over the parent/child intercomm."""
    prog = os.path.join(REPO, "tests", "progs", "spawn_parent_prog.py")
    cmd = [sys.executable, "-m", "mvapich2_tpu.run", "-np", "2",
           sys.executable, prog]
    r = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                       timeout=180)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout

/* cabi_ext_test.c — exercises the extended C ABI surface: info objects,
 * comm/win/type attributes with copy/delete callbacks, user-defined
 * reduction ops, pack/unpack, group set operations, comm names,
 * create_group, split_type, intercomm create/merge, nonblocking
 * collectives, Waitsome/Testany. Prints "No Errors" on success
 * (the reference suite's contract, test/mpi/runtests.in). */
#include <mpi.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

static int errs = 0;

#define CHECK(cond) do { if (!(cond)) { \
    fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
    errs++; } } while (0)

static int delete_calls = 0;

static int my_copy(MPI_Comm c, int k, void *es, void *in, void *out,
                   int *flag) {
    (void)c; (void)k;
    CHECK(es == (void *)0x42);
    *(void **)out = (char *)in + 1;   /* copied value = old + 1 */
    *flag = 1;
    return MPI_SUCCESS;
}

static int my_delete(MPI_Comm c, int k, void *val, void *es) {
    (void)c; (void)k; (void)val;
    CHECK(es == (void *)0x42);
    delete_calls++;
    return MPI_SUCCESS;
}

static void user_max3(void *invec, void *inoutvec, int *len,
                      MPI_Datatype *dt) {
    (void)dt;
    int *a = invec, *b = inoutvec;
    for (int i = 0; i < *len; i++)
        b[i] = a[i] > b[i] ? a[i] : b[i];
}

int main(int argc, char **argv) {
    int rank, size;
    MPI_Init(&argc, &argv);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);

    /* ---- info ---- */
    MPI_Info info;
    MPI_Info_create(&info);
    MPI_Info_set(info, "file", "runfile");
    MPI_Info_set(info, "soft", "host");
    int nkeys = -1, flag = 0, vlen = -1;
    char val[MPI_MAX_INFO_VAL];
    MPI_Info_get_nkeys(info, &nkeys);
    CHECK(nkeys == 2);
    MPI_Info_get(info, "file", MPI_MAX_INFO_VAL - 1, val, &flag);
    CHECK(flag && strcmp(val, "runfile") == 0);
    MPI_Info_get_valuelen(info, "soft", &vlen, &flag);
    CHECK(flag && vlen == 4);
    MPI_Info info2;
    MPI_Info_dup(info, &info2);
    MPI_Info_delete(info2, "file");
    MPI_Info_get(info2, "file", MPI_MAX_INFO_VAL - 1, val, &flag);
    CHECK(!flag);
    MPI_Info_get(info, "file", MPI_MAX_INFO_VAL - 1, val, &flag);
    CHECK(flag);   /* dup is a deep copy */
    MPI_Info_free(&info);
    MPI_Info_free(&info2);

    /* ---- predefined attributes ---- */
    int *tag_ub = NULL;
    MPI_Comm_get_attr(MPI_COMM_WORLD, MPI_TAG_UB, &tag_ub, &flag);
    CHECK(flag && *tag_ub >= 32767);

    /* ---- user keyvals + copy/delete on dup/free ---- */
    int kv;
    MPI_Comm_create_keyval(my_copy, my_delete, &kv, (void *)0x42);
    MPI_Comm_set_attr(MPI_COMM_WORLD, kv, (void *)100);
    void *got = NULL;
    MPI_Comm_get_attr(MPI_COMM_WORLD, kv, &got, &flag);
    CHECK(flag && got == (void *)100);
    MPI_Comm dup;
    MPI_Comm_dup(MPI_COMM_WORLD, &dup);
    MPI_Comm_get_attr(dup, kv, &got, &flag);
    CHECK(flag && got == (void *)101);   /* my_copy added 1 */
    /* a new (dup'ed) comm is unnamed until MPI_Comm_set_name (§6.8) */
    {
        char dn[MPI_MAX_OBJECT_NAME];
        int dl = -1;
        MPI_Comm_get_name(dup, dn, &dl);
        CHECK(dl == 0);
        MPI_Comm_set_name(dup, "mydup");
        MPI_Comm_get_name(dup, dn, &dl);
        CHECK(dl == 5 && strcmp(dn, "mydup") == 0);
    }
    int before = delete_calls;
    MPI_Comm_free(&dup);
    CHECK(delete_calls == before + 1);
    MPI_Comm_delete_attr(MPI_COMM_WORLD, kv);
    MPI_Comm_get_attr(MPI_COMM_WORLD, kv, &got, &flag);
    CHECK(!flag);
    MPI_Comm_free_keyval(&kv);
    CHECK(kv == MPI_KEYVAL_INVALID);

    /* ---- comm names ---- */
    char name[MPI_MAX_OBJECT_NAME];
    int rlen;
    MPI_Comm_get_name(MPI_COMM_WORLD, name, &rlen);
    CHECK(strcmp(name, "MPI_COMM_WORLD") == 0);

    /* ---- group set operations ---- */
    MPI_Group wg, evens, odds, un, inter, diff;
    MPI_Comm_group(MPI_COMM_WORLD, &wg);
    int nev = (size + 1) / 2;
    int ranges[1][3] = {{0, size - 1, 2}};
    MPI_Group_range_incl(wg, 1, ranges, &evens);
    int gsz;
    MPI_Group_size(evens, &gsz);
    CHECK(gsz == nev);
    MPI_Group_range_excl(wg, 1, ranges, &odds);
    MPI_Group_size(odds, &gsz);
    CHECK(gsz == size - nev);
    MPI_Group_union(evens, odds, &un);
    MPI_Group_size(un, &gsz);
    CHECK(gsz == size);
    MPI_Group_intersection(evens, odds, &inter);
    MPI_Group_size(inter, &gsz);
    CHECK(gsz == 0);
    MPI_Group_difference(wg, odds, &diff);
    int cmp;
    MPI_Group_compare(diff, evens, &cmp);
    CHECK(cmp == MPI_IDENT);

    /* ---- create_group: only members call ---- */
    if (rank % 2 == 0) {
        MPI_Comm ec;
        MPI_Comm_create_group(MPI_COMM_WORLD, evens, 3, &ec);
        CHECK(ec != MPI_COMM_NULL);
        int esz;
        MPI_Comm_size(ec, &esz);
        CHECK(esz == nev);
        int sum = -1, mine = 1;
        MPI_Allreduce(&mine, &sum, 1, MPI_INT, MPI_SUM, ec);
        CHECK(sum == nev);
        MPI_Comm_free(&ec);
    }

    /* ---- split_type ---- */
    MPI_Comm node;
    MPI_Comm_split_type(MPI_COMM_WORLD, MPI_COMM_TYPE_SHARED, 0,
                        MPI_INFO_NULL, &node);
    CHECK(node != MPI_COMM_NULL);
    MPI_Comm_free(&node);

    /* ---- user-defined op (non-commutative-safe path) ---- */
    MPI_Op op;
    MPI_Op_create(user_max3, 0, &op);
    int commute = -1;
    MPI_Op_commutative(op, &commute);
    CHECK(commute == 0);
    int mine2[2] = {rank, size - rank}, out2[2] = {-1, -1};
    MPI_Allreduce(mine2, out2, 2, MPI_INT, op, MPI_COMM_WORLD);
    CHECK(out2[0] == size - 1 && out2[1] == size);
    int red[2] = {-1, -1};
    MPI_Reduce(mine2, red, 2, MPI_INT, op, 0, MPI_COMM_WORLD);
    if (rank == 0)
        CHECK(red[0] == size - 1 && red[1] == size);
    int scanv[1] = {rank}, scano[1] = {-1};
    MPI_Scan(scanv, scano, 1, MPI_INT, op, MPI_COMM_WORLD);
    CHECK(scano[0] == rank);   /* max of 0..rank */
    MPI_Op_free(&op);
    CHECK(op == MPI_OP_NULL);

    /* ---- pack/unpack round trip with a vector type ---- */
    MPI_Datatype vec;
    MPI_Type_vector(3, 2, 4, MPI_INT, &vec);
    MPI_Type_commit(&vec);
    int src[12], dst[12], packed_sz = 0;
    for (int i = 0; i < 12; i++) { src[i] = 100 + i; dst[i] = -1; }
    MPI_Pack_size(1, vec, MPI_COMM_WORLD, &packed_sz);
    CHECK(packed_sz == 6 * (int)sizeof(int));
    char pbuf[64];
    int pos = 0;
    MPI_Pack(src, 1, vec, pbuf, sizeof pbuf, &pos, MPI_COMM_WORLD);
    CHECK(pos == packed_sz);
    pos = 0;
    MPI_Unpack(pbuf, sizeof pbuf, &pos, dst, 1, vec, MPI_COMM_WORLD);
    for (int blk = 0; blk < 3; blk++)
        for (int j = 0; j < 2; j++)
            CHECK(dst[4 * blk + j] == 100 + 4 * blk + j);
    MPI_Aint tlb, text;
    MPI_Type_get_true_extent(vec, &tlb, &text);
    CHECK(tlb == 0 && text == 10 * (int)sizeof(int));
    MPI_Type_free(&vec);

    /* ---- type dup + attributes ---- */
    MPI_Datatype ctg, ctg2;
    MPI_Type_contiguous(4, MPI_INT, &ctg);
    MPI_Type_commit(&ctg);
    int tkv;
    MPI_Type_create_keyval(MPI_TYPE_DUP_FN, MPI_TYPE_NULL_DELETE_FN,
                           &tkv, NULL);
    MPI_Type_set_attr(ctg, tkv, (void *)7);
    MPI_Type_dup(ctg, &ctg2);
    MPI_Type_get_attr(ctg2, tkv, &got, &flag);
    CHECK(flag && got == (void *)7);
    MPI_Type_free(&ctg);
    MPI_Type_free(&ctg2);
    MPI_Type_free_keyval(&tkv);

    /* ---- intercomm create + merge (needs >= 2 ranks) ---- */
    if (size >= 2) {
        int color = rank < size / 2 ? 0 : 1;
        MPI_Comm half;
        MPI_Comm_split(MPI_COMM_WORLD, color, rank, &half);
        int rleader = color == 0 ? size / 2 : 0;
        MPI_Comm inter_c, merged;
        /* peer_comm is significant only at the leaders (§6.6.2) */
        int hrank;
        MPI_Comm_rank(half, &hrank);
        MPI_Comm peer = hrank == 0 ? MPI_COMM_WORLD : MPI_COMM_NULL;
        MPI_Intercomm_create(half, 0, peer, rleader, 99, &inter_c);
        int is_inter = 0, rsize = 0;
        MPI_Comm_test_inter(inter_c, &is_inter);
        CHECK(is_inter);
        MPI_Comm_remote_size(inter_c, &rsize);
        CHECK(rsize == (color == 0 ? size - size / 2 : size / 2));
        MPI_Intercomm_merge(inter_c, color, &merged);
        int msz;
        MPI_Comm_size(merged, &msz);
        CHECK(msz == size);
        MPI_Comm_free(&merged);
        MPI_Comm_free(&inter_c);
        MPI_Comm_free(&half);
    }

    /* ---- nonblocking collectives ---- */
    MPI_Request req;
    MPI_Ibarrier(MPI_COMM_WORLD, &req);
    MPI_Wait(&req, MPI_STATUS_IGNORE);
    int bval = rank == 0 ? 31337 : -1;
    MPI_Ibcast(&bval, 1, MPI_INT, 0, MPI_COMM_WORLD, &req);
    MPI_Wait(&req, MPI_STATUS_IGNORE);
    CHECK(bval == 31337);
    int isum = -1, one = 1;
    MPI_Iallreduce(&one, &isum, 1, MPI_INT, MPI_SUM, MPI_COMM_WORLD,
                   &req);
    MPI_Wait(&req, MPI_STATUS_IGNORE);
    CHECK(isum == size);

    /* ---- Waitsome / Testany over pt2pt ---- */
    if (size >= 2) {
        if (rank == 0) {
            int r0 = -1, r1 = -1;
            MPI_Request rr[2];
            MPI_Irecv(&r0, 1, MPI_INT, 1, 5, MPI_COMM_WORLD, &rr[0]);
            MPI_Irecv(&r1, 1, MPI_INT, 1, 6, MPI_COMM_WORLD, &rr[1]);
            int outcount = 0, indices[2], done = 0;
            while (done < 2) {
                MPI_Status sts[2];
                MPI_Waitsome(2, rr, &outcount, indices, sts);
                CHECK(outcount != MPI_UNDEFINED);
                done += outcount;
            }
            CHECK(r0 == 50 && r1 == 60);
        } else if (rank == 1) {
            int v0 = 50, v1 = 60;
            MPI_Send(&v0, 1, MPI_INT, 0, 5, MPI_COMM_WORLD);
            MPI_Send(&v1, 1, MPI_INT, 0, 6, MPI_COMM_WORLD);
        }
    }

    /* ---- env extras ---- */
    int fin = -1, thr = -1, main_th = -1;
    MPI_Finalized(&fin);
    CHECK(fin == 0);
    MPI_Query_thread(&thr);
    CHECK(thr >= MPI_THREAD_SINGLE && thr <= MPI_THREAD_MULTIPLE);
    MPI_Is_thread_main(&main_th);
    CHECK(main_th == 1);
    char lib[MPI_MAX_LIBRARY_VERSION_STRING];
    MPI_Get_library_version(lib, &rlen);
    CHECK(rlen > 0);

    /* ---- dynamic error classes ---- */
    int eclass, ecode;
    MPI_Add_error_class(&eclass);
    CHECK(eclass > MPI_ERR_LASTCODE);
    MPI_Add_error_code(eclass, &ecode);
    MPI_Add_error_string(ecode, "my custom failure");
    char es[MPI_MAX_ERROR_STRING];
    MPI_Error_string(ecode, es, &rlen);
    CHECK(strcmp(es, "my custom failure") == 0);

    MPI_Group_free(&wg);
    MPI_Group_free(&evens);
    MPI_Group_free(&odds);
    MPI_Group_free(&un);
    MPI_Group_free(&inter);
    MPI_Group_free(&diff);

    /* aggregate errs across ranks so a failure anywhere is visible */
    int total = 0;
    MPI_Allreduce(&errs, &total, 1, MPI_INT, MPI_SUM, MPI_COMM_WORLD);
    if (rank == 0) {
        if (total == 0)
            printf("No Errors\n");
        else
            printf("Found %d errors\n", total);
    }
    MPI_Finalize();
    return total ? 1 : 0;
}

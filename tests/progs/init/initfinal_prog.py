"""Init/Finalize/world attributes/Wtime (ref: init/initstat, timer tests)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import mtest
from mvapich2_tpu import mpi

mtest.check(not mpi.Initialized(), "not initialized before Init")
comm = mtest.init()
mtest.check(mpi.Initialized(), "Initialized after Init")
mtest.check(not mpi.Finalized(), "not finalized yet")

t0 = mpi.Wtime()
t1 = mpi.Wtime()
mtest.check(t1 >= t0, "Wtime monotonic")
mtest.check(mpi.Wtick() > 0, "Wtick positive")

name = mpi.Get_processor_name()
mtest.check(isinstance(name, str) and name, "processor name")

ver, subver = mpi.Get_version()
mtest.check(ver >= 3, "MPI version >= 3")
lib = mpi.Get_library_version()
mtest.check("mvapich2_tpu" in lib or "MVAPICH" in lib.upper(),
            "library version string")

self_comm = mpi.COMM_SELF
mtest.check_eq(self_comm.size, 1, "COMM_SELF size")
import numpy as np
out = self_comm.allreduce(np.array([5.0]))
mtest.check_eq(out[0], 5.0, "COMM_SELF allreduce")

mtest.finalize()

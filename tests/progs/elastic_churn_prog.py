"""Elastic rank churn under load (ROADMAP item 3's serving scenario):
the resident world keeps an allreduce load running while session
worlds JOIN (MPI_Comm_spawn), do one intercomm exchange, and LEAVE
(disconnect) — repeatedly. Measures sustained join/leave cycles/s.

argv[1] = number of cycles (default 3). Prints per-cycle timings, the
cycles/s rate, and 'No Errors' from rank 0. The warm-attach daemon
(MV2T_DAEMON=1) serves the resident world's segments; the child
worlds' bootstrap rides the same KVS.

Extends the ft/ dup/split-churn tests into the sustained elastic
shape (tests/test_ft.py::test_elastic_join_leave_under_load)."""

import os
import sys
import time

sys.path.insert(0, ".")

import numpy as np  # noqa: E402

from mvapich2_tpu import mpi  # noqa: E402

CYCLES = int(sys.argv[1]) if len(sys.argv) > 1 else 3

mpi.Init()
comm = mpi.COMM_WORLD
child = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "elastic_churn_child.py")

errs = 0
per_cycle = []
for i in range(CYCLES):
    t0 = time.perf_counter()
    # resident load: the serving world keeps computing while a session
    # joins — collectives before, between, and after the join
    out = comm.allreduce(np.full(1024, 1.0 + i))
    if out[0] != comm.size * (1.0 + i):
        errs += 1
        print(f"rank {comm.rank}: load allreduce wrong at cycle {i}")
    inter, codes = mpi.Comm_spawn([sys.executable, child], maxprocs=1,
                                  root=0, comm=comm)
    if any(codes):
        errs += 1
        print(f"rank {comm.rank}: cycle {i} spawn codes {codes}")
    # one session exchange (intercomm semantics: each side receives the
    # OTHER group's reduction — the child contributes 1000)
    got = inter.allreduce(np.array([comm.rank], dtype=np.int64))
    if int(got[0]) != 1000:
        errs += 1
        print(f"rank {comm.rank}: cycle {i} inter allreduce {got[0]} "
              f"!= 1000")
    inter.disconnect()
    out = comm.allreduce(np.ones(8))
    if out[0] != float(comm.size):
        errs += 1
        print(f"rank {comm.rank}: post-leave allreduce wrong at {i}")
    per_cycle.append(time.perf_counter() - t0)

total = sum(per_cycle)
if comm.rank == 0:
    print(f"elastic: {CYCLES} join/leave cycles under load, "
          f"{CYCLES / total:.2f} cycles/s "
          f"(per-cycle {['%.2f' % s for s in per_cycle]})")
tot = comm.allreduce(np.array([errs], dtype=np.int64))
mpi.Finalize()
if comm.rank == 0 and int(tot[0]) == 0:
    print("No Errors")
sys.exit(1 if errs else 0)

/* cabi_test2.c — conformance for the widened C ABI surface:
 * v-collectives, derived datatypes, send modes, probe, waitany/testall,
 * persistent requests, scan/exscan, comm/group extras, RMA atomics,
 * error strings. Prints "No Errors" on success (runtests contract). */
#include <mpi.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

static int errs = 0;

#define CHECK(cond, msg) do { \
    if (!(cond)) { errs++; fprintf(stderr, "rank %d: %s\n", rank, msg); } \
} while (0)

int main(int argc, char **argv) {
    int rank, size;
    MPI_Init(&argc, &argv);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);

    /* ---- allgatherv with displs (reversed layout) ---- */
    {
        int *rcounts = malloc(size * sizeof(int));
        int *displs = malloc(size * sizeof(int));
        int total = 0;
        for (int i = 0; i < size; i++) { rcounts[i] = i + 1; total += i + 1; }
        int off = total;
        for (int i = 0; i < size; i++) { off -= rcounts[i]; displs[i] = off; }
        double *sb = malloc((rank + 1) * sizeof(double));
        for (int i = 0; i <= rank; i++) sb[i] = rank * 10.0;
        double *rb = calloc(total, sizeof(double));
        MPI_Allgatherv(sb, rank + 1, MPI_DOUBLE, rb, rcounts, displs,
                       MPI_DOUBLE, MPI_COMM_WORLD);
        for (int i = 0; i < size; i++)
            for (int k = 0; k < rcounts[i]; k++)
                CHECK(rb[displs[i] + k] == i * 10.0, "allgatherv payload");
        free(sb); free(rb); free(rcounts); free(displs);
    }

    /* ---- alltoallv ---- */
    {
        int *sc = malloc(size * sizeof(int)), *sd = malloc(size * sizeof(int));
        int *rc = malloc(size * sizeof(int)), *rd = malloc(size * sizeof(int));
        int stot = 0, rtot = 0;
        for (int j = 0; j < size; j++) {
            sc[j] = j + 1; sd[j] = stot; stot += sc[j];
            rc[j] = rank + 1; rd[j] = rtot; rtot += rc[j];
        }
        int *sb = malloc(stot * sizeof(int));
        for (int j = 0; j < size; j++)
            for (int k = 0; k < sc[j]; k++)
                sb[sd[j] + k] = rank * 100 + j;
        int *rb = calloc(rtot, sizeof(int));
        MPI_Alltoallv(sb, sc, sd, MPI_INT, rb, rc, rd, MPI_INT,
                      MPI_COMM_WORLD);
        for (int i = 0; i < size; i++)
            for (int k = 0; k < rc[i]; k++)
                CHECK(rb[rd[i] + k] == i * 100 + rank, "alltoallv payload");
        free(sb); free(rb); free(sc); free(sd); free(rc); free(rd);
    }

    /* ---- reduce_scatter (irregular counts) ---- */
    {
        int *rcounts = malloc(size * sizeof(int));
        int total = 0;
        for (int i = 0; i < size; i++) { rcounts[i] = i + 1; total += i + 1; }
        double *sb = malloc(total * sizeof(double));
        for (int i = 0; i < total; i++) sb[i] = (double)i;
        double *rb = calloc(rcounts[rank], sizeof(double));
        MPI_Reduce_scatter(sb, rb, rcounts, MPI_DOUBLE, MPI_SUM,
                           MPI_COMM_WORLD);
        int off = 0;
        for (int i = 0; i < rank; i++) off += rcounts[i];
        for (int k = 0; k < rcounts[rank]; k++)
            CHECK(rb[k] == (double)(off + k) * size, "reduce_scatter");
        free(sb); free(rb); free(rcounts);
    }

    /* ---- scatterv ---- */
    {
        int *sc = malloc(size * sizeof(int));
        int *dp = malloc(size * sizeof(int));
        int total = 0;
        for (int i = 0; i < size; i++) {
            sc[i] = i + 1; dp[i] = total; total += sc[i];
        }
        double *sb = NULL;
        if (rank == 0) {
            sb = malloc(total * sizeof(double));
            for (int i = 0; i < total; i++) sb[i] = (double)i * 3.0;
        }
        double *rb = calloc(rank + 1, sizeof(double));
        MPI_Scatterv(sb, sc, dp, MPI_DOUBLE, rb, rank + 1, MPI_DOUBLE, 0,
                     MPI_COMM_WORLD);
        for (int k = 0; k <= rank; k++)
            CHECK(rb[k] == (double)(dp[rank] + k) * 3.0, "scatterv");
        free(sb); free(rb); free(sc); free(dp);
    }

    /* ---- scan / exscan ---- */
    {
        long v = rank + 1, out = 0;
        MPI_Scan(&v, &out, 1, MPI_LONG, MPI_SUM, MPI_COMM_WORLD);
        CHECK(out == (long)(rank + 1) * (rank + 2) / 2, "scan");
        long ex = -1;
        MPI_Exscan(&v, &ex, 1, MPI_LONG, MPI_SUM, MPI_COMM_WORLD);
        if (rank > 0)
            CHECK(ex == (long)rank * (rank + 1) / 2, "exscan");
    }

    /* ---- derived datatypes: vector over the wire ---- */
    if (size >= 2 && rank < 2) {
        MPI_Datatype vec;
        MPI_Type_vector(4, 1, 2, MPI_DOUBLE, &vec);
        MPI_Type_commit(&vec);
        int tsz; MPI_Type_size(vec, &tsz);
        CHECK(tsz == 4 * 8, "type_size(vector)");
        MPI_Aint lb, ext; MPI_Type_get_extent(vec, &lb, &ext);
        CHECK(ext == 7 * 8, "type_extent(vector)");
        double buf[8], got[8];
        for (int i = 0; i < 8; i++) { buf[i] = rank * 50.0 + i; got[i] = -1.0; }
        int peer = 1 - rank;
        MPI_Sendrecv(buf, 1, vec, peer, 11, got, 1, vec, peer, 11,
                     MPI_COMM_WORLD, MPI_STATUS_IGNORE);
        for (int i = 0; i < 8; i += 2)
            CHECK(got[i] == peer * 50.0 + i, "vector sendrecv strided");
        CHECK(got[1] == -1.0, "vector gap untouched");
        MPI_Type_free(&vec);

        MPI_Datatype ctg;
        MPI_Type_contiguous(3, MPI_INT, &ctg);
        MPI_Type_commit(&ctg);
        int ib[6] = {0}, ig[6] = {0};
        for (int i = 0; i < 6; i++) ib[i] = rank * 7 + i;
        MPI_Sendrecv(ib, 2, ctg, peer, 12, ig, 2, ctg, peer, 12,
                     MPI_COMM_WORLD, MPI_STATUS_IGNORE);
        for (int i = 0; i < 6; i++)
            CHECK(ig[i] == peer * 7 + i, "contiguous(3) x2");
        MPI_Type_free(&ctg);
    }

    /* ---- derived datatypes in collectives ---- */
    {
        MPI_Datatype pair;
        MPI_Type_contiguous(2, MPI_INT, &pair);
        MPI_Type_commit(&pair);
        /* bcast of 3 pair elements */
        int pb[6];
        for (int i = 0; i < 6; i++) pb[i] = (rank == 0) ? 70 + i : -1;
        MPI_Bcast(pb, 3, pair, 0, MPI_COMM_WORLD);
        for (int i = 0; i < 6; i++)
            CHECK(pb[i] == 70 + i, "bcast derived");
        /* allgatherv of 1 pair per rank, reversed displs */
        int *rc2 = malloc(size * sizeof(int));
        int *dp2 = malloc(size * sizeof(int));
        for (int i = 0; i < size; i++) {
            rc2[i] = 1; dp2[i] = size - 1 - i;
        }
        int mine2[2] = {rank * 2, rank * 2 + 1};
        int *gath = calloc(2 * size, sizeof(int));
        MPI_Allgatherv(mine2, 1, pair, gath, rc2, dp2, pair,
                       MPI_COMM_WORLD);
        for (int i = 0; i < size; i++) {
            CHECK(gath[2 * dp2[i]] == i * 2, "allgatherv derived lo");
            CHECK(gath[2 * dp2[i] + 1] == i * 2 + 1,
                  "allgatherv derived hi");
        }
        /* allreduce on a homogeneous derived type */
        int ar[4], arr_out[4];
        for (int i = 0; i < 4; i++) ar[i] = rank + i;
        MPI_Allreduce(ar, arr_out, 2, pair, MPI_SUM, MPI_COMM_WORLD);
        for (int i = 0; i < 4; i++)
            CHECK(arr_out[i] == size * i + size * (size - 1) / 2,
                  "allreduce derived");
        MPI_Type_free(&pair);
        free(rc2); free(dp2); free(gath);
    }

    /* ---- ssend / probe / iprobe ---- */
    if (size >= 2 && rank < 2) {
        int peer = 1 - rank;
        if (rank == 0) {
            double d[5] = {1, 2, 3, 4, 5};
            MPI_Ssend(d, 5, MPI_DOUBLE, 1, 21, MPI_COMM_WORLD);
        } else {
            MPI_Status st;
            MPI_Probe(0, 21, MPI_COMM_WORLD, &st);
            int n; MPI_Get_count(&st, MPI_DOUBLE, &n);
            CHECK(n == 5, "probe count");
            double d[5];
            MPI_Recv(d, 5, MPI_DOUBLE, 0, 21, MPI_COMM_WORLD, &st);
            CHECK(d[4] == 5.0, "ssend payload");
            int flag = 1;
            MPI_Iprobe(0, 99, MPI_COMM_WORLD, &flag, &st);
            CHECK(flag == 0, "iprobe empty");
        }
        (void)peer;
    }

    /* ---- waitany / testall ---- */
    if (size >= 2 && rank < 2) {
        int peer = 1 - rank;
        MPI_Request reqs[4];
        int rbuf[4][2], sbuf[4][2];
        for (int i = 0; i < 4; i++) {
            sbuf[i][0] = rank * 10 + i; sbuf[i][1] = i;
            MPI_Irecv(rbuf[i], 2, MPI_INT, peer, 30 + i, MPI_COMM_WORLD,
                      &reqs[i]);
        }
        MPI_Request sreqs[4];
        for (int i = 0; i < 4; i++)
            MPI_Isend(sbuf[i], 2, MPI_INT, peer, 30 + i, MPI_COMM_WORLD,
                      &sreqs[i]);
        int seen = 0;
        while (seen < 4) {
            int idx; MPI_Status st;
            MPI_Waitany(4, reqs, &idx, &st);
            if (idx == MPI_UNDEFINED) break;
            CHECK(rbuf[idx][0] == peer * 10 + idx, "waitany payload");
            CHECK(st.MPI_TAG == 30 + idx, "waitany status tag");
            CHECK(st.MPI_SOURCE == peer, "waitany status source");
            seen++;
        }
        CHECK(seen == 4, "waitany drained");
        int flag = 0;
        while (!flag)
            MPI_Testall(4, sreqs, &flag, MPI_STATUSES_IGNORE);
    }

    /* ---- persistent requests ---- */
    if (size >= 2 && rank < 2) {
        int peer = 1 - rank;
        double sb[4], rb[4];
        MPI_Request ps, pr;
        MPI_Send_init(sb, 4, MPI_DOUBLE, peer, 40, MPI_COMM_WORLD, &ps);
        MPI_Recv_init(rb, 4, MPI_DOUBLE, peer, 40, MPI_COMM_WORLD, &pr);
        /* wait/test on an INACTIVE persistent request returns at once */
        int inf = 0;
        MPI_Test(&ps, &inf, MPI_STATUS_IGNORE);
        CHECK(inf == 1, "inactive persistent test");
        MPI_Wait(&ps, MPI_STATUS_IGNORE);
        CHECK(ps != MPI_REQUEST_NULL, "inactive persistent wait");
        for (int round = 0; round < 3; round++) {
            for (int i = 0; i < 4; i++) sb[i] = rank * 1000 + round;
            MPI_Start(&pr);
            MPI_Start(&ps);
            MPI_Wait(&ps, MPI_STATUS_IGNORE);
            MPI_Wait(&pr, MPI_STATUS_IGNORE);
            CHECK(rb[0] == peer * 1000 + round, "persistent round");
        }
        /* complete a round via MPI_Test: handle must stay restartable */
        for (int i = 0; i < 4; i++) sb[i] = rank * 1000 + 99;
        MPI_Start(&pr);
        MPI_Start(&ps);
        int pf = 0;
        while (!pf) MPI_Test(&pr, &pf, MPI_STATUS_IGNORE);
        CHECK(pr != MPI_REQUEST_NULL, "persistent survives Test");
        MPI_Wait(&ps, MPI_STATUS_IGNORE);
        CHECK(rb[0] == peer * 1000 + 99, "persistent via Test");
        MPI_Start(&pr);
        MPI_Start(&ps);
        MPI_Wait(&ps, MPI_STATUS_IGNORE);
        MPI_Wait(&pr, MPI_STATUS_IGNORE);
        CHECK(rb[0] == peer * 1000 + 99, "persistent restart after Test");
        MPI_Request_free(&ps);
        MPI_Request_free(&pr);
    }

    /* ---- comm/group extras ---- */
    {
        MPI_Comm dup;
        MPI_Comm_dup(MPI_COMM_WORLD, &dup);
        int cmp;
        MPI_Comm_compare(MPI_COMM_WORLD, dup, &cmp);
        CHECK(cmp == MPI_CONGRUENT, "comm_compare dup");
        MPI_Comm_compare(MPI_COMM_WORLD, MPI_COMM_WORLD, &cmp);
        CHECK(cmp == MPI_IDENT, "comm_compare self");

        MPI_Group wg, evens;
        MPI_Comm_group(MPI_COMM_WORLD, &wg);
        int gs; MPI_Group_size(wg, &gs);
        CHECK(gs == size, "group_size");
        int n_even = (size + 1) / 2;
        int *er = malloc(n_even * sizeof(int));
        for (int i = 0; i < n_even; i++) er[i] = 2 * i;
        MPI_Group_incl(wg, n_even, er, &evens);
        MPI_Comm sub;
        MPI_Comm_create(MPI_COMM_WORLD, evens, &sub);
        if (rank % 2 == 0) {
            CHECK(sub != MPI_COMM_NULL, "comm_create member");
            int sr; MPI_Comm_rank(sub, &sr);
            CHECK(sr == rank / 2, "comm_create rank");
            MPI_Comm_free(&sub);
        } else {
            CHECK(sub == MPI_COMM_NULL, "comm_create nonmember");
        }
        int tr_in[1] = {0}, tr_out[1] = {-5};
        MPI_Group_translate_ranks(evens, 1, tr_in, wg, tr_out);
        CHECK(tr_out[0] == 0, "translate_ranks");
        MPI_Group_free(&evens);
        MPI_Group_free(&wg);
        MPI_Comm_free(&dup);
        free(er);
    }

    /* ---- RMA atomics ---- */
    {
        long lbuf[2] = {0, 0};
        MPI_Win win;
        MPI_Win_create(lbuf, 2 * sizeof(long), sizeof(long),
                       MPI_INFO_NULL, MPI_COMM_WORLD, &win);
        MPI_Win_fence(0, win);
        long one = 1 + rank;
        MPI_Accumulate(&one, 1, MPI_LONG, 0, 0, 1, MPI_LONG, MPI_SUM, win);
        MPI_Win_fence(0, win);
        if (rank == 0)
            CHECK(lbuf[0] == (long)size * (size + 1) / 2, "accumulate");

        long ticket = -1, inc = 1;
        MPI_Win_lock(MPI_LOCK_SHARED, 0, 0, win);
        MPI_Fetch_and_op(&inc, &ticket, MPI_LONG, 0, 1, MPI_SUM, win);
        MPI_Win_unlock(0, win);
        MPI_Barrier(MPI_COMM_WORLD);
        CHECK(ticket >= 0 && ticket < size, "fetch_and_op ticket");
        if (rank == 0)
            CHECK(lbuf[1] == size, "fetch_and_op total");
        MPI_Win_free(&win);
    }

    /* ---- error strings ---- */
    {
        char msg[MPI_MAX_ERROR_STRING];
        int len = 0;
        MPI_Error_string(MPI_ERR_RANK, msg, &len);
        CHECK(len > 0 && strlen(msg) > 0, "error_string");
        int cls = -1;
        MPI_Error_class(MPI_ERR_TRUNCATE, &cls);
        CHECK(cls == MPI_ERR_TRUNCATE, "error_class");
    }

    int tot = 0;
    MPI_Allreduce(&errs, &tot, 1, MPI_INT, MPI_SUM, MPI_COMM_WORLD);
    if (rank == 0 && tot == 0)
        printf("No Errors\n");
    MPI_Finalize();
    return tot ? 1 : 0;
}

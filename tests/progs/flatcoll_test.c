/* flatcoll_test.c — correctness sweep of the small-message collective
 * datapath through the unmodified C ABI: allreduce/reduce/bcast/barrier
 * across ops x dtypes x sizes that straddle every protocol boundary
 * (flat-slot payload max 4 KiB, the eager size, FP_COLL_MAX), plus
 * dup'd and split comms so the per-(context, lane) flat regions and
 * the call-numbering bases are exercised across comm lifetimes.
 * Prints "No Errors" from rank 0 on success; any rank exits 1 on a
 * validation failure. Run at np in {2,3,4,8}. */
#include <mpi.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

static int errs;

static void fail(const char *what, long size, int rep) {
    fprintf(stderr, "FAIL %s size=%ld rep=%d\n", what, size, rep);
    errs++;
}

/* sizes (bytes) straddling the tier boundaries: flat max (4096), the
 * eager size (32 KiB default), FP_COLL_MAX (256 KiB default) */
static const long SIZES[] = {4,     256,   4096,   4100,  8192,
                             32768, 32772, 262144, 262148, 524288};
#define NSIZES ((int)(sizeof(SIZES) / sizeof(SIZES[0])))

static void sweep_int_allreduce(MPI_Comm comm) {
    int rank, np;
    MPI_Comm_rank(comm, &rank);
    MPI_Comm_size(comm, &np);
    for (int si = 0; si < NSIZES; si++) {
        long n = SIZES[si] / (long)sizeof(int);
        if (n < 1)
            n = 1;
        int *sb = malloc(n * sizeof(int));
        int *rb = malloc(n * sizeof(int));
        for (int rep = 0; rep < 3; rep++) {
            for (long i = 0; i < n; i++)
                sb[i] = (int)(i % 911) + rank + rep;
            memset(rb, -1, n * sizeof(int));
            MPI_Allreduce(sb, rb, (int)n, MPI_INT, MPI_SUM, comm);
            for (long i = 0; i < n; i++) {
                int want = np * ((int)(i % 911) + rep)
                           + np * (np - 1) / 2;
                if (rb[i] != want) {
                    fail("allreduce-sum-int", SIZES[si], rep);
                    break;
                }
            }
            /* MAX with IN_PLACE */
            for (long i = 0; i < n; i++)
                rb[i] = rank == np - 1 ? 7 + (int)(i % 13) : -rank;
            MPI_Allreduce(MPI_IN_PLACE, rb, (int)n, MPI_INT, MPI_MAX,
                          comm);
            for (long i = 0; i < n; i++)
                if (rb[i] != 7 + (int)(i % 13)) {
                    fail("allreduce-max-inplace", SIZES[si], rep);
                    break;
                }
        }
        free(sb);
        free(rb);
    }
}

static void sweep_dtypes(MPI_Comm comm) {
    int rank, np;
    MPI_Comm_rank(comm, &rank);
    MPI_Comm_size(comm, &np);
    /* double SUM */
    double dv[64], dr[64];
    for (int i = 0; i < 64; i++)
        dv[i] = 0.5 * rank + i;
    MPI_Allreduce(dv, dr, 64, MPI_DOUBLE, MPI_SUM, comm);
    for (int i = 0; i < 64; i++) {
        double want = 0.5 * np * (np - 1) / 2 + (double)np * i;
        if (dr[i] < want - 1e-9 || dr[i] > want + 1e-9)
            fail("allreduce-sum-double", 64 * 8, i);
    }
    /* float MIN */
    float fv[9], fr[9];
    for (int i = 0; i < 9; i++)
        fv[i] = (float)(rank + 1) * (i + 1);
    MPI_Allreduce(fv, fr, 9, MPI_FLOAT, MPI_MIN, comm);
    for (int i = 0; i < 9; i++)
        if (fr[i] != (float)(i + 1))
            fail("allreduce-min-float", 9 * 4, i);
    /* long long PROD over 1s and one 2 */
    long long lv[3], lr[3];
    for (int i = 0; i < 3; i++)
        lv[i] = rank == 0 ? 2 : 1;
    MPI_Allreduce(lv, lr, 3, MPI_LONG_LONG, MPI_PROD, comm);
    for (int i = 0; i < 3; i++)
        if (lr[i] != 2)
            fail("allreduce-prod-ll", 24, i);
    /* unsigned char BOR */
    unsigned char cv[17], cr[17];
    for (int i = 0; i < 17; i++)
        cv[i] = (unsigned char)(1u << (rank % 8));
    MPI_Allreduce(cv, cr, 17, MPI_UNSIGNED_CHAR, MPI_BOR, comm);
    unsigned char wantc = 0;
    for (int r = 0; r < np; r++)
        wantc |= (unsigned char)(1u << (r % 8));
    for (int i = 0; i < 17; i++)
        if (cr[i] != wantc)
            fail("allreduce-bor-uchar", 17, i);
    /* short MAX, reduce to each root in turn */
    short sv[5], sr[5];
    for (int root = 0; root < np; root++) {
        for (int i = 0; i < 5; i++)
            sv[i] = (short)(10 * rank + i);
        memset(sr, 0, sizeof(sr));
        MPI_Reduce(sv, sr, 5, MPI_SHORT, MPI_MAX, root, comm);
        if (rank == root)
            for (int i = 0; i < 5; i++)
                if (sr[i] != (short)(10 * (np - 1) + i))
                    fail("reduce-max-short", 10, root);
    }
}

static void sweep_bcast_barrier(MPI_Comm comm) {
    int rank, np;
    MPI_Comm_rank(comm, &rank);
    MPI_Comm_size(comm, &np);
    for (int si = 0; si < NSIZES; si++) {
        long nb = SIZES[si];
        char *buf = malloc(nb);
        for (int root = 0; root < np; root++) {
            if (rank == root)
                for (long i = 0; i < nb; i++)
                    buf[i] = (char)((i + root) % 127);
            else
                memset(buf, 0, nb);
            MPI_Bcast(buf, (int)nb, MPI_CHAR, root, comm);
            for (long i = 0; i < nb; i++)
                if (buf[i] != (char)((i + root) % 127)) {
                    fail("bcast", nb, root);
                    break;
                }
            MPI_Barrier(comm);
        }
        free(buf);
    }
}

int main(int argc, char **argv) {
    MPI_Init(&argc, &argv);
    int rank, np;
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &np);

    sweep_int_allreduce(MPI_COMM_WORLD);
    sweep_dtypes(MPI_COMM_WORLD);
    sweep_bcast_barrier(MPI_COMM_WORLD);

    /* dup: fresh collective context -> fresh flat region + base */
    MPI_Comm dup;
    MPI_Comm_dup(MPI_COMM_WORLD, &dup);
    sweep_dtypes(dup);
    MPI_Comm_free(&dup);

    /* split into halves: disjoint sibling comms share one allocated
     * context id — the flat lane (min member ring index) must keep
     * their regions apart */
    if (np >= 2) {
        MPI_Comm half;
        MPI_Comm_split(MPI_COMM_WORLD, rank % 2, rank, &half);
        sweep_dtypes(half);
        sweep_int_allreduce(half);
        MPI_Comm_free(&half);
    }
    /* context reuse: the freed split's id returns to the pool; a new
     * split must renumber cleanly from the region's carried-over seq */
    if (np >= 2) {
        MPI_Comm half2;
        MPI_Comm_split(MPI_COMM_WORLD, rank % 2, rank, &half2);
        sweep_dtypes(half2);
        MPI_Comm_free(&half2);
    }

    int total = 0;
    MPI_Allreduce(&errs, &total, 1, MPI_INT, MPI_SUM, MPI_COMM_WORLD);
    if (rank == 0)
        printf(total == 0 ? "No Errors\n" : "%d errors\n", total);
    MPI_Finalize();
    return total == 0 ? 0 : 1;
}

"""Split/free churn across real rank processes (comm/ctxsplit.c's
shape): exercises the fused native agreement (cp_coll_gather — one
C-engine gather carrying color/key/world + the guarded context-id
payload) plus id recycling through Comm.free.

Launched by tests via: python -m mvapich2_tpu.run -np N <this file> [iters]
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")
from mvapich2_tpu import mpi  # noqa: E402

mpi.Init()
comm = mpi.COMM_WORLD
rank, size = comm.rank, comm.size

errs = 0
iters = int(sys.argv[1]) if len(sys.argv) > 1 else 300

ids = set()
t0 = time.perf_counter()
for i in range(iters):
    # same color everywhere, key=-rank: full comm, reversed order
    sub = comm.split(i % 3, key=-rank)
    if sub is None or sub.size != size or sub.rank != size - 1 - rank:
        errs += 1
        print(f"rank {rank}: bad split result at iter {i}")
        break
    ids.add(sub.context_id)
    got = sub.allreduce(np.array([1], np.int64))
    if got[0] != size:
        errs += 1
        print(f"rank {rank}: allreduce on split comm wrong: {got[0]}")
        break
    sub.free()
    # mixed membership: alternating ranks sit out with UNDEFINED
    part = comm.split(None if (rank + i) % 2 else 1)
    if (rank + i) % 2:
        if part is not None:
            errs += 1
            print(f"rank {rank}: UNDEFINED split returned a comm")
            break
    else:
        if part is None or part.size != (size + 1 - (i % 2)) // 2:
            errs += 1
            print(f"rank {rank}: partial split wrong size")
            break
        part.free()
elapsed = time.perf_counter() - t0

# freed ids recycle through the availability mask: the churn must reuse
# a tiny pool, not grow with the iteration count
if len(ids) > 8:
    errs += 1
    print(f"rank {rank}: context ids leaked: {len(ids)} distinct")

comm.barrier()
if rank == 0 and errs == 0:
    print(f"No Errors ({iters} split/free in {elapsed:.2f}s)")
mpi.Finalize()
sys.exit(1 if errs else 0)

"""THREAD_MULTIPLE: concurrent per-thread tag lanes (ref: threads/pt2pt/
multisend)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import threading
import numpy as np
import mtest
from mvapich2_tpu import mpi

comm = mtest.init(mpi.THREAD_MULTIPLE)
r, s = comm.rank, comm.size
NT = 4
fails = []

if s >= 2 and r < 2:
    peer = 1 - r

    def worker(t):
        try:
            for round_ in range(5):
                sb = np.full(16, float(1000 * t + round_ + r))
                rb = np.zeros(16)
                comm.sendrecv(sb, peer, 100 + t, rb, peer, 100 + t)
                if not np.array_equal(
                        rb, np.full(16, float(1000 * t + round_ + peer))):
                    fails.append((t, round_))
        except Exception as e:       # noqa: BLE001
            fails.append((t, repr(e)))

    ts = [threading.Thread(target=worker, args=(t,)) for t in range(NT)]
    for th in ts:
        th.start()
    for th in ts:
        th.join()
    mtest.check(not fails, f"thread lanes: {fails[:3]}")

comm.barrier()
mtest.finalize()

"""Standalone program: 4x4 device-mesh collective sweep on 16 fake
host devices (ISSUE 20 multi-axis tier).

The test-suite conftest pins XLA to 8 host devices, so the 4x4 grid
cannot run in-process there; this program re-exports the platform
flags BEFORE importing jax and drives run_ranks itself.

Launched via: python tests/progs/hier_mesh16_prog.py
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
os.environ["MV2T_DEVICE_COLL_MIN_BYTES"] = "1"

sys.path.insert(0, ".")

import numpy as np                                  # noqa: E402
import jax                                          # noqa: E402

from mvapich2_tpu.runtime.universe import run_ranks  # noqa: E402
from mvapich2_tpu.parallel.mesh import make_mesh     # noqa: E402

N = 16
COUNTS = (1024, 1025, 4096)


def app(comm):
    ch = comm.device_channel
    assert ch.multi_axis and ch.axes == ("x", "y"), ch.axes
    for cnt in COUNTS:
        x = (np.arange(cnt) % 251 + comm.rank + 1).astype(np.float32)
        out = np.asarray(comm.allreduce(x)).reshape(-1)
        ref = sum((np.arange(cnt) % 251 + r + 1).astype(np.float32)
                  for r in range(N))
        np.testing.assert_array_equal(out, ref)
    b = np.full(512, float(comm.rank), np.float32)
    comm.bcast(b, root=5)
    assert b[0] == 5.0 and b[-1] == 5.0
    g = np.empty(N * 256, np.float32)
    comm.allgather(np.full(256, float(comm.rank + 10), np.float32), g)
    for r in range(N):
        assert g[r * 256] == r + 10
    return True


mesh = make_mesh((4, 4), ("x", "y"), jax.devices()[:16])
res = run_ranks(N, app, device_mesh=mesh, timeout=600)
assert all(res)
print("No Errors")

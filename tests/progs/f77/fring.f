C     fring.f — f77 conformance smoke: ring sendrecv + allreduce +
C     bcast + wtime. Prints 'No Errors' on rank 0 (runtests contract).
      PROGRAM FRING
      IMPLICIT NONE
      INCLUDE 'mpif.h'
      INTEGER IERR, RANK, SIZE, LEFT, RIGHT, I, ERRS
      INTEGER STATUS(MPI_STATUS_SIZE)
      INTEGER SBUF(8), RBUF(8)
      DOUBLE PRECISION V(4), W(4), T0, T1
      ERRS = 0
      CALL MPI_INIT(IERR)
      CALL MPI_COMM_RANK(MPI_COMM_WORLD, RANK, IERR)
      CALL MPI_COMM_SIZE(MPI_COMM_WORLD, SIZE, IERR)
      RIGHT = MOD(RANK + 1, SIZE)
      LEFT = MOD(RANK + SIZE - 1, SIZE)
      DO 10 I = 1, 8
         SBUF(I) = RANK * 100 + I
         RBUF(I) = -1
 10   CONTINUE
      CALL MPI_SENDRECV(SBUF, 8, MPI_INTEGER, RIGHT, 5,
     $     RBUF, 8, MPI_INTEGER, LEFT, 5,
     $     MPI_COMM_WORLD, STATUS, IERR)
      DO 20 I = 1, 8
         IF (RBUF(I) .NE. LEFT * 100 + I) ERRS = ERRS + 1
 20   CONTINUE
      IF (STATUS(MPI_SOURCE) .NE. LEFT) ERRS = ERRS + 1
      IF (STATUS(MPI_TAG) .NE. 5) ERRS = ERRS + 1
      DO 30 I = 1, 4
         V(I) = DBLE(RANK + I)
 30   CONTINUE
      T0 = MPI_WTIME()
      CALL MPI_ALLREDUCE(V, W, 4, MPI_DOUBLE_PRECISION, MPI_SUM,
     $     MPI_COMM_WORLD, IERR)
      T1 = MPI_WTIME()
      IF (T1 .LT. T0) ERRS = ERRS + 1
      DO 40 I = 1, 4
         IF (ABS(W(I) - DBLE(SIZE * I + SIZE * (SIZE - 1) / 2))
     $        .GT. 1D-9) ERRS = ERRS + 1
 40   CONTINUE
      IF (RANK .EQ. 0) THEN
         DO 50 I = 1, 8
            SBUF(I) = 700 + I
 50      CONTINUE
      ENDIF
      CALL MPI_BCAST(SBUF, 8, MPI_INTEGER, 0, MPI_COMM_WORLD, IERR)
      DO 60 I = 1, 8
         IF (SBUF(I) .NE. 700 + I) ERRS = ERRS + 1
 60   CONTINUE
      CALL MPI_ALLREDUCE(ERRS, I, 1, MPI_INTEGER, MPI_SUM,
     $     MPI_COMM_WORLD, IERR)
      IF (RANK .EQ. 0 .AND. I .EQ. 0) THEN
         PRINT *, 'No Errors'
      ENDIF
      CALL MPI_FINALIZE(IERR)
      END

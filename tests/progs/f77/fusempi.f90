! fusempi.f90 -- `use mpi` (f90 module) ring + allreduce check.
! Exercises the generated module's explicit interfaces (mpi_comm_rank,
! mpi_probe with IMPORTed MPI_STATUS_SIZE) and an external
! choice-buffer routine (mpi_allreduce).
program fusempi
  use mpi
  implicit none
  integer :: ierr, rank, nproc, val, total, expect
  integer :: status(MPI_STATUS_SIZE)
  integer :: left, right, token

  call mpi_init(ierr)
  call mpi_comm_rank(MPI_COMM_WORLD, rank, ierr)
  call mpi_comm_size(MPI_COMM_WORLD, nproc, ierr)

  val = rank + 1
  total = -1
  call mpi_allreduce(val, total, 1, MPI_INTEGER, MPI_SUM, &
                     MPI_COMM_WORLD, ierr)
  expect = nproc * (nproc + 1) / 2
  if (total /= expect) then
     print *, 'allreduce mismatch', total, expect
     call mpi_abort(MPI_COMM_WORLD, 1, ierr)
  end if

  left = mod(rank + nproc - 1, nproc)
  right = mod(rank + 1, nproc)
  token = rank
  if (rank == 0) then
     call mpi_send(token, 1, MPI_INTEGER, right, 7, MPI_COMM_WORLD, ierr)
     call mpi_recv(token, 1, MPI_INTEGER, left, 7, MPI_COMM_WORLD, &
                   status, ierr)
  else
     call mpi_recv(token, 1, MPI_INTEGER, left, 7, MPI_COMM_WORLD, &
                   status, ierr)
     call mpi_send(token, 1, MPI_INTEGER, right, 7, MPI_COMM_WORLD, ierr)
  end if

  if (rank == 0) then
     if (token /= nproc - 1) then
        print *, 'ring token mismatch', token
        call mpi_abort(MPI_COMM_WORLD, 1, ierr)
     end if
     print *, ' No Errors'
  end if
  call mpi_finalize(ierr)
end program fusempi

"""FT fault-injection program: kill a member MID split/dup churn.

Exercises the mixed C-gather/Python-fallback unwind in the fused
comm-management collective (native/cplane.cpp cp_coll_gather): ranks
hammer split+dup+free on COMM_WORLD (the cp_coll_gather fast path when
the shm plane owns the comm); rank 1 SIGKILLs itself mid-churn, so
survivors meet the failure INSIDE an exchange — some unwound by the C
engine's -2 verdict (peer record never arrives, failure mark observed
in the wait loop), some by the python path's ULFM recv checks after a
member diverged — and every survivor must surface a clean
MPIX_ERR_PROC_FAILED, then ack + shrink + finish a collective.

Run: python -m mvapich2_tpu.run -np 4 --ft python ft_churn_prog.py
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, ".")
from mvapich2_tpu import mpi  # noqa: E402
from mvapich2_tpu.core.errors import (MPIException,  # noqa: E402
                                      MPIX_ERR_PROC_FAILED)

mpi.Init()
comm = mpi.COMM_WORLD
rank, size = comm.rank, comm.size

KILL_AT = 25          # churn iterations before rank 1 dies
errs = 0
hit_failure = False

deadline = time.time() + 60
i = 0
while time.time() < deadline:
    if rank == 1 and i == KILL_AT:
        # die like a crashed process, mid-churn: survivors may already
        # be inside the next split's gather when detection lands
        import signal
        os.kill(os.getpid(), signal.SIGKILL)
    try:
        sub = comm.split(i % 2 if rank != 0 else 0, rank)
        d = sub.dup()
        d.free()
        sub.free()
    except MPIException as e:
        if e.error_class != MPIX_ERR_PROC_FAILED:
            errs += 1
            print(f"rank {rank}: churn error class {e.error_class}, "
                  f"not MPIX_ERR_PROC_FAILED (iter {i})")
        hit_failure = True
        break
    i += 1

if not hit_failure:
    errs += 1
    print(f"rank {rank}: never saw the failure ({i} iterations)")

# the failure must (eventually) be attributed to rank 1
wait_end = time.time() + 30
while 1 not in comm.u.failed_ranks and time.time() < wait_end:
    time.sleep(0.02)
if 1 not in comm.u.failed_ranks:
    errs += 1
    print(f"rank {rank}: rank 1 never in failed set: "
          f"{comm.u.failed_ranks}")

# survivors recover: ack, shrink, and run a collective + another split
comm.failure_ack()
newcomm = comm.shrink()
if newcomm.size != size - 1:
    errs += 1
    print(f"rank {rank}: shrunk size {newcomm.size} != {size - 1}")
out = newcomm.allreduce(np.full(4, 1.0))
if abs(out[0] - (size - 1)) > 1e-9:
    errs += 1
    print(f"rank {rank}: allreduce on shrunk comm wrong: {out[0]}")
post = newcomm.split(0, newcomm.rank)   # churn machinery still sound
if post.size != newcomm.size:
    errs += 1
    print(f"rank {rank}: post-shrink split size {post.size}")
post.free()

newcomm.barrier()
if newcomm.rank == 0 and errs == 0:
    print("No Errors")
sys.exit(1 if errs else 0)

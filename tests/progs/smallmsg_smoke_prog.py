"""Rank program: small-message datapath perf smoke (np=4).

Times an 8-byte ping-pong (ranks 0<->1) and a 4-byte allreduce across
all four ranks, printing per-call averages. The harness
(tests/test_perf_smoke.py) asserts both stay under generous wall
budgets — the r5 regression this guards was a 3x latency loss from the
spin budget collapsing on every doorbell wake, and a 311 us 4-byte
allreduce from the envelope-per-hop collective schedule.

Launched via: python -m mvapich2_tpu.run -np 4 tests/progs/smallmsg_smoke_prog.py
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")
from mvapich2_tpu import mpi                        # noqa: E402

mpi.Init()
comm = mpi.COMM_WORLD
rank, size = comm.rank, comm.size

errs = 0

# --- 8-byte ping-pong between ranks 0 and 1 -------------------------
pp_iters = 200
buf = np.full(1, float(rank), dtype=np.float64)
out = np.zeros(1, dtype=np.float64)
comm.barrier()
if rank == 0:
    for _ in range(20):
        comm.send(buf, 1, tag=7)
        comm.recv(out, 1, tag=8)
    t0 = time.perf_counter()
    for _ in range(pp_iters):
        comm.send(buf, 1, tag=7)
        comm.recv(out, 1, tag=8)
    pp_us = (time.perf_counter() - t0) / pp_iters / 2 * 1e6
    if out[0] != 1.0:
        errs += 1
        print(f"rank 0: pingpong payload wrong ({out[0]})")
elif rank == 1:
    for _ in range(20 + pp_iters):
        comm.recv(out, 0, tag=7)
        comm.send(buf, 0, tag=8)

# --- 4-byte allreduce across all ranks ------------------------------
ar_iters = 200
s = np.full(1, np.int32(rank + 1))
r = np.zeros(1, np.int32)
for _ in range(20):
    comm.allreduce(s, r)
comm.barrier()
t0 = time.perf_counter()
for _ in range(ar_iters):
    comm.allreduce(s, r)
comm.barrier()
ar_us = (time.perf_counter() - t0) / ar_iters * 1e6

expect = size * (size + 1) // 2
if r[0] != expect:
    errs += 1
    print(f"rank {rank}: allreduce wrong (got {r[0]}, want {expect})")

if rank == 0:
    print(f"pingpong_8B_halfrtt_us={pp_us:.1f}")
    print(f"allreduce_4B_avg_us={ar_us:.1f}")
    if errs == 0:
        print("No Errors")
mpi.Finalize()
sys.exit(1 if errs else 0)

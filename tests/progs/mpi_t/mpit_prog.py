"""MPI_T tools interface: cvar enumeration/read, pvar sessions
(ref: mpi_t/mpi_t_str, cvarwrite)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import mtest
from mvapich2_tpu import mpit

comm = mtest.init()

n = mpit.cvar_get_num()
mtest.check(n > 10, f"cvar count {n}")
for i in range(n):
    info = mpit.cvar_get_info(i)
    mtest.check("name" in info and info["name"], f"cvar {i} info")
    mpit.cvar_read(i)   # must not raise

idx = mpit.cvar_get_index("ALLREDUCE_ALGO")
mtest.check(idx >= 0, "known cvar index")

npv = mpit.pvar_get_num()
mtest.check(npv > 0, "pvar count")
sess = mpit.pvar_session_create()
h = sess.handle_alloc("recvq_match_attempts")
sess.start(h)

# drive some traffic so counters move
import numpy as np
comm.allreduce(np.ones(128))
comm.barrier()
v = sess.read(h)
mtest.check(v >= 0, "pvar session delta")
sess.handle_free(h)

cats = mpit.category_names()
mtest.check(len(cats) >= 1, "categories exist")

mtest.finalize()

"""Rank program: large-message allreduce perf smoke.

Times a handful of 1 MiB allreduces at np=4 and prints the per-call
average. The harness (tests/test_perf_smoke.py) asserts the average
stays under a generous wall-clock budget — the scratch-file cliff this
guards against was ~33 ms/call (BENCH_OSU_r05), an order of magnitude
over the budget, so the check is variance-proof while still catching
any silent return of per-send staging files.

Launched via: python -m mvapich2_tpu.run -np 4 tests/progs/allreduce_smoke_prog.py
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")
from mvapich2_tpu import mpi                        # noqa: E402

mpi.Init()
comm = mpi.COMM_WORLD
rank, size = comm.rank, comm.size

n = (1 << 20) // 4              # 1 MiB of float32
sbuf = np.full(n, float(rank + 1), dtype=np.float32)
rbuf = np.zeros(n, dtype=np.float32)
expect = float(sum(range(1, size + 1)))

# warmup (segment/arena construction, tuning-table touch)
for _ in range(3):
    comm.allreduce(sbuf, rbuf, mpi.SUM)

iters = 10
comm.barrier()
t0 = time.perf_counter()
for _ in range(iters):
    comm.allreduce(sbuf, rbuf, mpi.SUM)
comm.barrier()
dt = time.perf_counter() - t0

errs = 0
if not np.all(rbuf == expect):
    errs += 1
    print(f"rank {rank}: allreduce result wrong "
          f"(got {rbuf[0]}, want {expect})")

if rank == 0:
    print(f"allreduce_1MiB_avg_us={dt / iters * 1e6:.1f}")
    if errs == 0:
        print("No Errors")
mpi.Finalize()
sys.exit(1 if errs else 0)

/* C-ABI conformance smoke: the "prints No Errors" contract (SURVEY §4)
 * exercised through libmpi.so — pt2pt, collectives, one-sided, from a
 * plain C program compiled with bin/mpicc. */
#include <mpi.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#define CHECK(x) do { if ((x) != MPI_SUCCESS) { \
    fprintf(stderr, "rank %d: %s failed\n", rank, #x); errs++; } } while (0)

int main(int argc, char **argv) {
    int rank, size, errs = 0;
    MPI_Init(&argc, &argv);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);

    /* pt2pt ring (eager) */
    long mine = rank, got = -1;
    MPI_Status st;
    MPI_Request rq;
    CHECK(MPI_Irecv(&got, 1, MPI_LONG, (rank - 1 + size) % size, 7,
                    MPI_COMM_WORLD, &rq));
    CHECK(MPI_Send(&mine, 1, MPI_LONG, (rank + 1) % size, 7,
                   MPI_COMM_WORLD));
    CHECK(MPI_Wait(&rq, &st));
    if (got != (rank - 1 + size) % size) {
        fprintf(stderr, "rank %d: ring got %ld\n", rank, got);
        errs++;
    }
    if (st.MPI_SOURCE != (rank - 1 + size) % size || st.MPI_TAG != 7)
        errs++;

    /* rendezvous-sized pt2pt */
    int big_n = 1 << 16;
    double *sb = malloc(big_n * sizeof(double));
    double *rb = malloc(big_n * sizeof(double));
    for (int i = 0; i < big_n; i++) sb[i] = rank + 0.5;
    CHECK(MPI_Irecv(rb, big_n, MPI_DOUBLE, (rank - 1 + size) % size, 8,
                    MPI_COMM_WORLD, &rq));
    CHECK(MPI_Send(sb, big_n, MPI_DOUBLE, (rank + 1) % size, 8,
                   MPI_COMM_WORLD));
    CHECK(MPI_Wait(&rq, MPI_STATUS_IGNORE));
    if (rb[big_n - 1] != (rank - 1 + size) % size + 0.5) errs++;

    /* collectives */
    double v = rank + 1, sum = 0;
    CHECK(MPI_Allreduce(&v, &sum, 1, MPI_DOUBLE, MPI_SUM,
                        MPI_COMM_WORLD));
    if (sum != size * (size + 1) / 2.0) {
        fprintf(stderr, "rank %d: allreduce %f\n", rank, sum);
        errs++;
    }
    int bval = rank == 0 ? 314 : 0;
    CHECK(MPI_Bcast(&bval, 1, MPI_INT, 0, MPI_COMM_WORLD));
    if (bval != 314) errs++;

    int *gat = malloc(size * sizeof(int));
    int me = rank * 7;
    CHECK(MPI_Allgather(&me, 1, MPI_INT, gat, 1, MPI_INT,
                        MPI_COMM_WORLD));
    for (int i = 0; i < size; i++)
        if (gat[i] != i * 7) errs++;

    CHECK(MPI_Barrier(MPI_COMM_WORLD));

    /* one-sided */
    void *base = NULL;
    MPI_Win win;
    CHECK(MPI_Win_allocate(64, 1, MPI_INFO_NULL, MPI_COMM_WORLD, &base,
                           &win));
    if (size >= 2 && rank == 0) {
        long payload = 4242;
        CHECK(MPI_Win_lock(MPI_LOCK_SHARED, 1, 0, win));
        CHECK(MPI_Put(&payload, 1, MPI_LONG, 1, 0, 1, MPI_LONG, win));
        CHECK(MPI_Win_unlock(1, win));
    }
    CHECK(MPI_Barrier(MPI_COMM_WORLD));
    if (size >= 2 && rank == 1) {
        long *p = (long *)base;
        if (p[0] != 4242) {
            fprintf(stderr, "rank 1: window has %ld\n", p[0]);
            errs++;
        }
    }
    CHECK(MPI_Win_free(&win));

    /* split */
    MPI_Comm half;
    CHECK(MPI_Comm_split(MPI_COMM_WORLD, rank % 2, rank, &half));
    int hrank, hsize;
    MPI_Comm_rank(half, &hrank);
    MPI_Comm_size(half, &hsize);
    if (hrank != rank / 2) errs++;
    CHECK(MPI_Comm_free(&half));

    int total = 0;
    MPI_Allreduce(&errs, &total, 1, MPI_INT, MPI_SUM, MPI_COMM_WORLD);
    if (rank == 0 && total == 0)
        printf("No Errors\n");
    MPI_Finalize();
    free(sb); free(rb); free(gat);
    return total ? 1 : 0;
}

"""One python-rank connect/disconnect cycle: Init, optional 4B
allreduce (argv[1] present), Finalize. The python twin of
benchmarks/c/churn_cycle.c for hosts without a C toolchain and for the
tier-1 churn smoke (tests/test_daemon.py)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import numpy as np  # noqa: E402

from mvapich2_tpu import mpi  # noqa: E402


def main() -> int:
    mpi.Init()
    if len(sys.argv) > 1:
        out = np.zeros(1, dtype=np.int32)
        mpi.COMM_WORLD.allreduce(np.ones(1, dtype=np.int32), out)
    mpi.Finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())

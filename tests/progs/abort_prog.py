"""Abort semantics: rank 1 calls MPI_Abort while others block — the
launcher must tear the whole job down (no hang). Driven by
tests/test_launcher.py, NOT the testlist (it exits nonzero by design)."""
import sys
import time

sys.path.insert(0, ".")
import numpy as np  # noqa: E402

from mvapich2_tpu import mpi  # noqa: E402

mpi.Init()
comm = mpi.COMM_WORLD
if comm.rank == 1:
    time.sleep(0.3)
    mpi.Abort(comm, 7)
# everyone else blocks forever in a recv that will never match: only
# the abort teardown can end the job
comm.recv(np.zeros(1), source=comm.rank, tag=12345)

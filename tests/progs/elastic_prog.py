"""Elastic-recovery smoke (SURVEY §5.3 migration analog): rank 1 dies;
survivors shrink + spawn a replacement + merge; state is restored to the
newcomer; prints 'No Errors'. Run under: mpirun --ft -np 3."""

import os
import sys
import time

import numpy as np

sys.path.insert(0, ".")
from mvapich2_tpu import mpi  # noqa: E402
from mvapich2_tpu.ft.elastic import rebuild_world  # noqa: E402

mpi.Init()
comm = mpi.COMM_WORLD
me = os.path.abspath(__file__)

parent = mpi.Comm_get_parent()
if parent is not None:
    # replacement incarnation: join the rebuilt world, receive state
    merged = parent.merge(high=True)
    state = np.zeros(4, np.float64)
    merged.bcast(state, root=0)
    assert state[0] == 123.0, state
    out = merged.allreduce(np.ones(1))
    assert int(out[0]) == merged.size
    merged.barrier()
    mpi.Finalize()
    sys.exit(0)

state = np.array([123.0, 4.0, 5.0, 6.0])   # application state to survive

# exercise the flat tier so the victim's region is live when it dies
# (rebuild must re-key, not reuse, the poisoned lane)
for _ in range(3):
    comm.allreduce(np.ones(1, np.int32))

# MV2T_ELASTIC_VICTIM=0 kills the flat-tier LEADER (lowest ring index:
# lane owner + fold rank + shm/arena segment creator) — the worst case
# for rebuild_world's re-keying
VICTIM = int(os.environ.get("MV2T_ELASTIC_VICTIM", "1"))
if comm.rank == VICTIM:
    os.kill(os.getpid(), 9)                # process failure (die.c analog)

# survivors: wait for launcher-driven detection (SURVEY §5.3)
for _ in range(600):
    if comm.get_failed().size > 0:
        break
    time.sleep(0.05)
assert comm.get_failed().size == 1, "failure not detected"

merged, lost = rebuild_world(comm, [sys.executable, me])
assert lost == 1 and merged.size == comm.size, (lost, merged.size)
merged.bcast(state, root=0)                # restore state to the newcomer
out = merged.allreduce(np.ones(1))
assert int(out[0]) == merged.size
merged.barrier()
if merged.rank == 0:
    print("No Errors")
mpi.Finalize()
sys.exit(0)

"""FT acceptance program (the analog of test/mpi/ft/revoke_shrink.c):
rank 1 dies mid-job; survivors detect it through the launcher's failure
events, ack, shrink, and finish a collective on the shrunken comm.

Run: python -m mvapich2_tpu.run -np 4 --ft python ft_shrink_prog.py
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, ".")
from mvapich2_tpu import mpi  # noqa: E402

mpi.Init()
comm = mpi.COMM_WORLD
rank, size = comm.rank, comm.size
u = comm.u

if rank == 1:
    # die like a crashed process (signal death = a *process failure*;
    # a plain sys.exit(1) is an application error and is not published)
    import signal
    os.kill(os.getpid(), signal.SIGKILL)

# wait for launcher-driven detection (KVS failure watcher)
deadline = time.time() + 30
while 1 not in u.failed_ranks:
    if time.time() > deadline:
        print(f"rank {rank}: failure of rank 1 never detected")
        sys.exit(1)
    time.sleep(0.02)

errs = 0

# sends to the dead rank must raise MPIX_ERR_PROC_FAILED
from mvapich2_tpu.core.errors import MPIX_ERR_PROC_FAILED, MPIException
try:
    comm.send(np.ones(1), dest=1)
    errs += 1
    print(f"rank {rank}: send to dead rank did not fail")
except MPIException as e:
    if e.error_class != MPIX_ERR_PROC_FAILED:
        errs += 1
        print(f"rank {rank}: wrong error class {e.error_class}")

# agree raises before ack, succeeds after
try:
    comm.agree(1)
    errs += 1
    print(f"rank {rank}: agree succeeded with unacked failure")
except MPIException:
    pass
comm.failure_ack()
acked = comm.failure_get_acked()
if list(acked.world_ranks) != [1]:
    errs += 1
    print(f"rank {rank}: acked group wrong: {acked.world_ranks}")
if comm.agree(1) != 1:
    errs += 1
    print(f"rank {rank}: agree value wrong")

# shrink and run a collective over the survivors
newcomm = comm.shrink()
if newcomm.size != size - 1:
    errs += 1
    print(f"rank {rank}: shrunk size {newcomm.size} != {size - 1}")
out = newcomm.allreduce(np.full(8, 1.0))
if abs(out[0] - (size - 1)) > 1e-9:
    errs += 1
    print(f"rank {rank}: allreduce on shrunk comm wrong: {out[0]}")

newcomm.barrier()
if newcomm.rank == 0 and errs == 0:
    print("No Errors")
sys.exit(1 if errs else 0)

"""Rank program: the fast-path observability counters (fastpath.c /
cp_flat_* via cp_fp_counter) are observable through an MPI_T pvar
session while the job runs — the regression tripwire the r5 verdict
asked for: a silent fast-path stand-down now shows as fp_fallback_*
moving while fp_coll_flat stays flat.

Launched via: python -m mvapich2_tpu.run -np 2 tests/progs/fp_pvar_prog.py
"""

import sys

import numpy as np

sys.path.insert(0, ".")
from mvapich2_tpu import mpi, mpit  # noqa: E402

mpi.Init()
comm = mpi.COMM_WORLD
rank, size = comm.rank, comm.size

NAMES = ("fp_coll_flat", "fp_coll_sched", "fp_hits", "fp_gil_takes",
         "fp_fallback_dtype", "fp_fallback_comm", "fp_fallback_size",
         "fp_fallback_plane", "fp_wait_spin", "fp_wait_bell")
sess = mpit.pvar_session_create()
handles = {n: sess.handle_alloc(n) for n in NAMES}
for h in handles.values():
    sess.start(h)

sbuf = np.arange(16, dtype=np.int32)
rbuf = np.zeros(16, dtype=np.int32)
for _ in range(5):
    comm.allreduce(sbuf, rbuf)
comm.barrier()

errs = 0
pch = getattr(comm.u, "plane_channel", None)
if pch is not None and pch.plane:
    flat = sess.read(handles["fp_coll_flat"])
    if pch._ring.lib.cp_flat_ok(pch.plane):
        # 5 allreduces + 1 barrier rode the flat-slot tier
        if flat < 6:
            errs += 1
            print(f"rank {rank}: fp_coll_flat did not move ({flat})")
    elif flat != 0:
        errs += 1
        print(f"rank {rank}: flat tier off but fp_coll_flat={flat}")
    for n in NAMES:
        if sess.read(handles[n]) < 0:
            errs += 1
            print(f"rank {rank}: {n} negative")
else:
    print(f"rank {rank}: (no native plane; fp pvars not exercised)")

for h in handles.values():
    sess.handle_free(h)

if rank == 0 and errs == 0:
    print("No Errors")
mpi.Finalize()
sys.exit(1 if errs else 0)

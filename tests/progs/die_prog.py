"""FT test helper: rank 1 dies mid-job (the analog of test/mpi/ft/die.c)."""

import os
import sys
import time

sys.path.insert(0, ".")
from mvapich2_tpu import mpi  # noqa: E402

mpi.Init()
comm = mpi.COMM_WORLD
if comm.rank == 1:
    os._exit(3)
# surviving ranks hang around; launcher must kill the job
time.sleep(30)
sys.exit(0)

"""comm dup/compare/free + context isolation (ref: comm/dup, ctxalloc)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest

comm = mtest.init()
r, s = comm.rank, comm.size

dup = comm.dup()
mtest.check_eq(dup.rank, r, "dup rank")
mtest.check_eq(dup.size, s, "dup size")
mtest.check_eq(comm.compare(dup), "congruent", "compare dup")
mtest.check_eq(comm.compare(comm), "ident", "compare self")

# context isolation: same tag on comm vs dup must not cross-match
if s >= 2 and r < 2:
    peer = 1 - r
    a = comm.isend(np.array([1], np.int32), peer, tag=7)
    b = dup.isend(np.array([2], np.int32), peer, tag=7)
    gd = np.zeros(1, np.int32)
    gc = np.zeros(1, np.int32)
    dup.recv(gd, peer, tag=7)
    comm.recv(gc, peer, tag=7)
    a.wait(); b.wait()
    mtest.check_eq(gc[0], 1, "world-context payload")
    mtest.check_eq(gd[0], 2, "dup-context payload")

# dup of dup, then free both
dd = dup.dup()
mtest.check_eq(dd.allreduce(np.array([1.0]))[0], float(s), "dup-dup coll")
dd.free()
dup.free()

mtest.finalize()

"""comm split colors/keys + split_type_shared (ref: comm/cmsplit*)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest
from mvapich2_tpu.core.comm import UNDEFINED

comm = mtest.init()
r, s = comm.rank, comm.size

# color by parity, key reversed: ranks ordered by descending world rank
sub = comm.split(r % 2, s - r)
n_same = (s + 1 - (r % 2)) // 2 if s % 2 else s // 2
mtest.check_eq(sub.size, n_same, "split size")
got = sub.allgather(np.array([r], np.int64))
want = sorted([i for i in range(s) if i % 2 == r % 2], reverse=True)
mtest.check_eq(got, want, "split ordering by key")
sub.free()

# UNDEFINED color: excluded ranks get None
sub2 = comm.split(0 if r == 0 else UNDEFINED, 0)
if r == 0:
    mtest.check(sub2 is not None and sub2.size == 1, "color-0 comm")
    sub2.free()
else:
    mtest.check(sub2 is None, "UNDEFINED color yields None")

# split_type_shared: all ranks of one node (here: all)
node = comm.split_type_shared()
mtest.check(node.size >= 1, "split_type_shared size")
tot = node.allreduce(np.array([1], np.int64))
mtest.check_eq(tot[0], node.size, "node-comm coll")
node.free()

mtest.finalize()

"""comm_create from subgroups + group ops (ref: comm/comm_create_group,
group/grouptest)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest
from mvapich2_tpu.core.group import Group

comm = mtest.init()
r, s = comm.rank, comm.size

world_g = comm.group
evens = Group([i for i in range(s) if i % 2 == 0])
sub = comm.create(evens)
if r % 2 == 0:
    mtest.check(sub is not None, "member got comm")
    mtest.check_eq(sub.rank, r // 2, "create rank order")
    tot = sub.allreduce(np.array([r], np.int64))
    mtest.check_eq(tot[0], sum(i for i in range(s) if i % 2 == 0),
                   "subcomm allreduce")
    sub.free()
else:
    mtest.check(sub is None, "non-member got None")

# group algebra
odds = world_g.difference(evens)
mtest.check_eq(odds.size, s // 2, "difference size")
uni = evens.union(odds)
mtest.check_eq(uni.size, s, "union size")
inter = evens.intersection(world_g)
mtest.check_eq(inter.size, (s + 1) // 2, "intersection size")
tr = world_g.translate_ranks(list(range(evens.size)), evens)
mtest.check(all(t is not None for t in tr), "translate")

mtest.finalize()

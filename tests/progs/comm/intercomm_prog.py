"""Intercommunicators: create/merge, remote group, inter-collectives
(ref: comm/ic1, icm, iccreate)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest
from mvapich2_tpu import mpi
from mvapich2_tpu.core.status import PROC_NULL, ROOT

comm = mtest.init()
r, s = comm.rank, comm.size

if s >= 2:
    half = comm.split(0 if r < (s + 1) // 2 else 1, r)
    lo = r < (s + 1) // 2
    inter = mpi.Intercomm_create(half, 0, comm, 0 if not lo else (s + 1) // 2)
    mtest.check_eq(inter.remote_size, s - half.size, "remote size")

    # inter bcast: low-group root 0 broadcasts to the high group
    buf = np.full(4, 5.0) if (lo and half.rank == 0) else np.zeros(4)
    if lo:
        root = ROOT if half.rank == 0 else PROC_NULL
    else:
        root = 0
    inter.bcast(buf, root=root)
    if not lo:
        mtest.check_eq(buf, np.full(4, 5.0), "inter bcast payload")

    # merge and verify total size; high group appended after low
    merged = mpi.Intercomm_merge(inter, high=not lo)
    mtest.check_eq(merged.size, s, "merged size")
    tot = merged.allreduce(np.array([1], np.int64))
    mtest.check_eq(tot[0], s, "merged coll")
    merged.free()
    half.free()

mtest.finalize()

"""Lock-order detector end-to-end: a deliberate A->B / B->A acquisition
cycle across two threads must produce EXACTLY ONE cycle report, naming
both acquisition sites, and the report must ride the watchdog dump path
(watchdog.build_report carries the monitor's section).

Launched via:
    MV2T_LOCKCHECK=1 python -m mvapich2_tpu.run -np 1 \
        tests/progs/lockcheck_cycle_prog.py
"""

import sys
import threading

sys.path.insert(0, ".")
from mvapich2_tpu import mpi, mpit  # noqa: E402
from mvapich2_tpu.analysis import lockorder  # noqa: E402
from mvapich2_tpu.trace import watchdog  # noqa: E402

mpi.Init()
comm = mpi.COMM_WORLD

errs = 0
mon = lockorder.get_monitor()
if mon is None:
    print("MV2T_LOCKCHECK is off; set it to 1 for this prog")
    errs += 1
else:
    lock_a = lockorder.tracked(threading.Lock(), "prog.lock_a")
    lock_b = lockorder.tracked(threading.Lock(), "prog.lock_b")

    def order_ab():
        with lock_a:
            with lock_b:     # edge lock_a -> lock_b
                pass

    def order_ba():
        with lock_b:
            with lock_a:     # edge lock_b -> lock_a: closes the cycle
                pass

    for fn in (order_ab, order_ba, order_ab, order_ba):  # repeats: no dup
        t = threading.Thread(target=fn)
        t.start()
        t.join()

    ncycles = int(mpit.pvar("lockcheck_cycles").read())
    if ncycles != 1 or len(mon.cycle_reports) != 1:
        print(f"expected exactly one cycle report, got pvar={ncycles} "
              f"reports={len(mon.cycle_reports)}")
        errs += 1
    else:
        report = mon.cycle_reports[0]
        # both lock sites must be named (file:line of each acquisition)
        for needle in ("prog.lock_a", "prog.lock_b",
                       "lockcheck_cycle_prog.py:"):
            if needle not in report:
                print(f"cycle report missing {needle!r}:\n{report}")
                errs += 1
    # the same evidence must surface through the watchdog dump path
    wd = watchdog.build_report(comm.u.engine)
    if "lock-order monitor" not in wd or "potential deadlock cycle" not in wd:
        print(f"watchdog report carries no lock-order section:\n{wd}")
        errs += 1
    if int(mpit.pvar("lockcheck_edges").read()) < 2:
        print("expected >= 2 recorded edges")
        errs += 1

mpi.Finalize()
if errs == 0 and comm.rank == 0:
    print(" No Errors")
sys.exit(1 if errs else 0)

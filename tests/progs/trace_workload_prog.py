"""Trace-workload program: allreduce + nonblocking collectives under the
event tracer — the bin/mpitrace acceptance workload. Every layer the
recorder instruments fires here: MPI entry/exit (interposition),
protocol (eager + rendezvous sendrecv), channel (shm/tcp packets),
progress (blocking waits), nbc (iallgather/ireduce DAG vertices).

Launched via: bin/mpitrace -np 4 python tests/progs/trace_workload_prog.py
"""

import sys

import numpy as np

sys.path.insert(0, ".")
from mvapich2_tpu import mpi  # noqa: E402

mpi.Init()
comm = mpi.COMM_WORLD
rank, size = comm.rank, comm.size
errs = 0

# blocking allreduce (mpi + protocol + channel + progress layers)
out = comm.allreduce(np.full(256, float(rank + 1)))
if abs(out[0] - sum(range(1, size + 1))) > 1e-9:
    errs += 1
    print(f"rank {rank}: allreduce wrong: {out[0]}")

# rendezvous-sized neighbor exchange (RTS/CTS/FIN protocol events)
big = np.full(1 << 17, float(rank), np.float64)
rbig = np.zeros(1 << 17, np.float64)
comm.sendrecv(big, (rank + 1) % size, 3, rbig, (rank - 1) % size, 3)
if rbig[0] != float((rank - 1) % size):
    errs += 1
    print(f"rank {rank}: big sendrecv wrong")

# NBC DAG schedules (nbc layer: vertex issue/complete)
rg = np.zeros(size, np.float64)
req = comm.iallgather(np.array([rank * 2.0]), rg)
rr = np.zeros(4, np.float64)
req2 = comm.ireduce(np.full(4, 1.0), rr, root=0)
req.wait()
req2.wait()
if rg.tolist() != [r * 2.0 for r in range(size)]:
    errs += 1
    print(f"rank {rank}: iallgather wrong: {rg}")
if rank == 0 and rr[0] != float(size):
    errs += 1
    print(f"rank {rank}: ireduce wrong: {rr[0]}")

comm.barrier()
if rank == 0 and errs == 0:
    print("No Errors")
mpi.Finalize()
sys.exit(1 if errs else 0)

"""struct/indexed/resized datatypes: pack/unpack + wire roundtrip
(ref: datatype/struct-pack, indexed tests)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest
from mvapich2_tpu.core import datatype as dt

comm = mtest.init()
r, s = comm.rank, comm.size

# indexed: scattered blocks
idx = dt.create_indexed([2, 1, 3], [0, 4, 9], dt.INT).commit()
src = np.arange(16, dtype=np.int32)
packed = idx.pack(src, 1)
mtest.check_eq(np.frombuffer(packed.tobytes(), np.int32),
               np.array([0, 1, 4, 9, 10, 11], np.int32), "indexed pack")
back = np.zeros(16, np.int32)
idx.unpack(packed, back, 1)
want = np.zeros(16, np.int32)
for b, d in ((2, 0), (1, 4), (3, 9)):
    want[d: d + b] = np.arange(d, d + b)
mtest.check_eq(back, want, "indexed unpack")

# struct over a heterogeneous record: int32 + 2x float64
rec = np.dtype([("a", np.int32), ("pad", np.int32), ("xy", np.float64, 2)])
st_dt = dt.create_struct([1, 2], [0, 8], [dt.INT, dt.DOUBLE]).commit()
buf = np.zeros(3, rec)
buf["a"] = [1, 2, 3]
buf["xy"] = [[1.5, 2.5], [3.5, 4.5], [5.5, 6.5]]
packed = st_dt.pack(buf, 3)
mtest.check_eq(len(packed), 3 * (4 + 16), "struct packed size")

out = np.zeros(3, rec)
st_dt.unpack(packed, out, 3)
mtest.check_eq(out["a"], buf["a"], "struct unpack ints")
mtest.check_eq(out["xy"], buf["xy"], "struct unpack doubles")

# resized: extent change affects count-striding
res = dt.create_resized(dt.create_contiguous(2, dt.DOUBLE), 0, 32).commit()
mtest.check_eq(res.extent, 32, "resized extent")
src2 = np.arange(8, dtype=np.float64)
p2 = res.pack(src2, 2)
mtest.check_eq(np.frombuffer(p2.tobytes(), np.float64),
               np.array([0.0, 1.0, 4.0, 5.0]), "resized pack")

# wire roundtrip of indexed type
if s >= 2 and r < 2:
    peer = 1 - r
    dst = np.zeros(16, np.int32)
    comm.sendrecv(src, peer, 7, dst, peer, 7,
                  send_count=1, send_datatype=idx,
                  recv_count=1, recv_datatype=idx)
    mtest.check_eq(dst, want, "indexed wire roundtrip")

comm.barrier()
mtest.finalize()

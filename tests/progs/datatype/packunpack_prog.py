"""MPI_Pack/Unpack/Pack_size API surface (ref: datatype/pack-tests)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest
from mvapich2_tpu import mpi
from mvapich2_tpu.core import datatype as dt

comm = mtest.init()
r, s = comm.rank, comm.size

sz = mpi.Pack_size(10, dt.DOUBLE)
mtest.check_eq(sz, 80, "Pack_size contiguous")

outbuf = np.zeros(200, np.uint8)
pos = 0
pos = mpi.Pack(np.arange(10, dtype=np.float64), 10, dt.DOUBLE, outbuf, pos)
mtest.check_eq(pos, 80, "Pack position")
pos = mpi.Pack(np.array([7, 8, 9], np.int32), 3, dt.INT, outbuf, pos)
mtest.check_eq(pos, 92, "Pack position 2")

d = np.zeros(10)
i = np.zeros(3, np.int32)
upos = 0
upos = mpi.Unpack(outbuf, upos, d, 10, dt.DOUBLE)
upos = mpi.Unpack(outbuf, upos, i, 3, dt.INT)
mtest.check_eq(d, np.arange(10, dtype=np.float64), "Unpack doubles")
mtest.check_eq(i, np.array([7, 8, 9], np.int32), "Unpack ints")
mtest.check_eq(upos, 92, "Unpack position")

# packed data is wire-compatible: send packed, recv typed
if s >= 2 and r < 2:
    peer = 1 - r
    if r == 0:
        comm.send(outbuf[:92], 1, tag=1)
    else:
        blob = np.zeros(92, np.uint8)
        comm.recv(blob, 0, tag=1)
        dd = np.zeros(10)
        mpi.Unpack(blob, 0, dd, 10, dt.DOUBLE)
        mtest.check_eq(dd, np.arange(10, dtype=np.float64),
                       "packed over wire")

comm.barrier()
mtest.finalize()

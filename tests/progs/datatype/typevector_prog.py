"""Derived datatypes over the wire: vector/hvector strided send/recv
(ref: datatype/transpose-style vector tests)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest
from mvapich2_tpu.core import datatype as dt

comm = mtest.init()
r, s = comm.rank, comm.size

if s >= 2 and r < 2:
    peer = 1 - r
    # send every other element of a 16-vector (one column of an 8x2 matrix)
    vec = dt.create_vector(8, 1, 2, dt.DOUBLE).commit()
    src = np.arange(16, dtype=np.float64) + 100 * r
    dstv = np.zeros(16)
    st = comm.sendrecv(src, peer, 1, dstv, peer, 1,
                       send_count=1, send_datatype=vec,
                       recv_count=1, recv_datatype=vec)
    want = np.zeros(16)
    want[0::2] = (np.arange(16, dtype=np.float64) + 100 * peer)[0::2]
    mtest.check_eq(dstv, want, "vector->vector")
    mtest.check_eq(st.get_count(vec), 1, "get_count(vector)")
    mtest.check_eq(st.get_elements(vec), 8, "get_elements(vector)")

    # vector send received as contiguous: strided gather on the send side
    dstc = np.zeros(8)
    if r == 0:
        comm.send(src, 1, tag=2, count=1, datatype=vec)
        comm.recv(dstc, 1, tag=3)
    else:
        comm.recv(dstc, 0, tag=2)
        mtest.check_eq(dstc, src[0::2] - 100 + 0, "vector->contig")
        comm.send(src, 0, tag=3, count=1, datatype=dt.create_contiguous(
            8, dt.DOUBLE).commit())
    if r == 0:
        mtest.check_eq(dstc, (np.arange(8, dtype=np.float64) + 100),
                       "contig(8) from rank1")

    # hvector with byte stride
    hv = dt.create_hvector(4, 2, 32, dt.DOUBLE).commit()
    hsrc = np.arange(16, dtype=np.float64) * (r + 1)
    hdst = np.zeros(16)
    comm.sendrecv(hsrc, peer, 4, hdst, peer, 4,
                  send_count=1, send_datatype=hv,
                  recv_count=1, recv_datatype=hv)
    want = np.zeros(16)
    for blk in range(4):
        want[blk * 4: blk * 4 + 2] = hsrc[blk * 4: blk * 4 + 2] \
            / (r + 1) * (peer + 1)
    mtest.check_eq(hdst, want, "hvector roundtrip")

comm.barrier()
mtest.finalize()

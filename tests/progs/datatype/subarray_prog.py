"""subarray datatype: 2-D halo-block exchange (ref: datatype/subarray,
the stencil ghost-cell pattern)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest
from mvapich2_tpu.core import datatype as dt

comm = mtest.init()
r, s = comm.rank, comm.size

# interior 4x4 block of an 8x8 grid
sub = dt.create_subarray([8, 8], [4, 4], [2, 2], dt.DOUBLE).commit()
grid = (np.arange(64, dtype=np.float64).reshape(8, 8) + 1000 * r)
packed = sub.pack(grid, 1)
mtest.check_eq(np.frombuffer(packed.tobytes(), np.float64),
               grid[2:6, 2:6].reshape(-1), "subarray pack")

if s >= 2 and r < 2:
    peer = 1 - r
    dstg = np.zeros((8, 8))
    comm.sendrecv(grid, peer, 3, dstg, peer, 3,
                  send_count=1, send_datatype=sub,
                  recv_count=1, recv_datatype=sub)
    want = np.zeros((8, 8))
    want[2:6, 2:6] = (np.arange(64, dtype=np.float64).reshape(8, 8)
                      + 1000 * peer)[2:6, 2:6]
    mtest.check_eq(dstg, want, "subarray exchange")

comm.barrier()
mtest.finalize()

"""Churn chaos (ROADMAP item 3 scenario): repeated split/dup comm churn
under allreduce load while a member — rank 0, the shm/arena LEADER —
dies mid-churn. Survivors must unwind (lease detection, MV2T_FT_WATCHER
off), revoke + shrink, and keep churning on the shrunken world; the
dead leader's shm state is reclaimed afterwards by the stale-segment
sweep (the harness verifies). Run under: mpirun -np 4 with
MPIEXEC_ALLOW_FAULT=1 and a crash fault armed on rank 0.
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")
from mvapich2_tpu import mpi                            # noqa: E402
from mvapich2_tpu.core.errors import (MPIException,     # noqa: E402
                                      MPIX_ERR_PROC_FAILED,
                                      MPIX_ERR_REVOKED)

ROUNDS = 12          # pre-failure budget (the victim dies well inside)
POST_ROUNDS = 3      # fixed post-recovery rounds: every survivor runs
                     # exactly these, whatever iteration it failed at,
                     # so the shrunken world's collectives line up

mpi.Init()
comm = mpi.COMM_WORLD


def churn_round(c, i):
    """One churn iteration: split, collective load on the halves, a
    world-wide rendezvous-sized allreduce, free."""
    sub = c.split(i % 2, c.rank)
    out = sub.allreduce(np.full(16, 1.0))
    assert out[0] == float(sub.size), out[0]
    big = c.allreduce(np.ones(1 << 15))          # 256 KiB load
    assert big[0] == float(c.size), big[0]
    d = sub.dup()
    d.free()
    sub.free()


err = None
t_detect = 0.0
for i in range(ROUNDS):
    t0 = time.perf_counter()
    try:
        churn_round(comm, i)
    except MPIException as e:
        assert e.error_class in (MPIX_ERR_PROC_FAILED, MPIX_ERR_REVOKED), \
            f"unexpected class {e.error_class}: {e}"
        err = e.error_class
        t_detect = time.perf_counter() - t0
        break

assert err is not None, "fault never fired (is MV2T_FAULTS armed?)"
if not comm.revoked:
    comm.revoke()
comm.failure_ack()
work = comm.shrink()
assert work.size == comm.size - 1, (work.size, comm.size)
for i in range(POST_ROUNDS):     # join/leave churn continues under load
    churn_round(work, i)

print(f"churn: rank={comm.rank} err={err} detect_s={t_detect:.2f} "
      f"shrunk={work.size}", flush=True)
if work.rank == 0:
    print("No Errors")
mpi.Finalize()
sys.exit(0)

/* ntrace_cabi_test.c — the C half of the mixed-ABI tracing workload
 * (ISSUE 10). A small, deterministic sequence of flat-tier collectives
 * and eager pt2pt through the unmodified C ABI, run under MV2T_TRACE
 * (+ the native ring) by tests/test_trace.py: the C ranks' MPI calls
 * never cross the interpreter, so their Perfetto lanes carry ONLY the
 * native C-plane events — proving the ring, not the python recorder,
 * is what made the fast path visible. tests/progs/mixed_trace_prog.py
 * runs the IDENTICAL sequence on the python ranks of the same job.
 * Prints "No Errors" from rank 0 on success. */
#include <mpi.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

#define N 16
#define PP 64
#define REPS 3

/* ISSUE 17: the metrics live-scrape test reuses this workload as the C
 * half of a mixed-ABI job and needs it to stay alive long enough for an
 * external bin/mpimetrics to attach — so the rep count and a per-rep
 * pause are env-tunable. Defaults keep the original 3-rep sequence
 * byte-identical for the tracing tests. */
static int env_int(const char *name, int dflt) {
    const char *v = getenv(name);
    return (v && atoi(v) > 0) ? atoi(v) : dflt;
}

int main(int argc, char **argv) {
    int rank, np, errs = 0;
    MPI_Init(&argc, &argv);
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &np);

    MPI_Barrier(MPI_COMM_WORLD);

    /* flat-tier allreduces (<=4 KiB, np<=8): fan-in/fold/fan-out */
    int reps = env_int("MV2T_TEST_CABI_REPS", REPS);
    int pause_us = env_int("MV2T_TEST_CABI_USLEEP", 0);
    int sb[N], rb[N];
    for (int rep = 0; rep < reps; rep++) {
        for (int i = 0; i < N; i++)
            sb[i] = 1 + rep;
        memset(rb, -1, sizeof(rb));
        MPI_Allreduce(sb, rb, N, MPI_INT, MPI_SUM, MPI_COMM_WORLD);
        for (int i = 0; i < N; i++)
            if (rb[i] != np * (1 + rep))
                errs++;
        if (pause_us)
            usleep(pause_us);
    }

    /* eager ping-pong with the partner rank (rank ^ 1) */
    if ((rank ^ 1) < np) {
        int peer = rank ^ 1;
        int pb[PP], qb[PP];
        for (int i = 0; i < PP; i++)
            pb[i] = rank * 1000 + i;
        if (rank % 2 == 0) {
            MPI_Send(pb, PP, MPI_INT, peer, 7, MPI_COMM_WORLD);
            MPI_Recv(qb, PP, MPI_INT, peer, 7, MPI_COMM_WORLD,
                     MPI_STATUS_IGNORE);
        } else {
            MPI_Recv(qb, PP, MPI_INT, peer, 7, MPI_COMM_WORLD,
                     MPI_STATUS_IGNORE);
            MPI_Send(pb, PP, MPI_INT, peer, 7, MPI_COMM_WORLD);
        }
        for (int i = 0; i < PP; i++)
            if (qb[i] != peer * 1000 + i)
                errs++;
    }

    MPI_Barrier(MPI_COMM_WORLD);

    int total = 0;
    MPI_Allreduce(&errs, &total, 1, MPI_INT, MPI_SUM, MPI_COMM_WORLD);
    if (rank == 0 && total == 0)
        printf("No Errors\n");
    MPI_Finalize();
    return total ? 1 : 0;
}

"""Lazy-wiring first-contact program: touch an unwired peer through
ONE chosen datapath shape and prove correctness + observability.

Mode (argv[1]):
  eager   4 B ring send/recv — must complete while the node is still
          UNWIRED (no agreement needed), then a collective wires it
  rndv    512 KiB pairwise exchange — first contact via the rendezvous
          ladder (degrades to scratch-file pre-wire, upgrades in place)
  flat    4 B allreduce loop — first contact via the flat-slot tier
          (the collective gate wires before tier choice)
  arena   1 MiB allreduce — first contact via the arena/CMA sectioned
          tier

Every rank asserts data correctness and that exactly one wire happened
on its shm channel, attributed to the expected pvar
(wiring_lazy by default; wiring_eager under MV2T_LAZY_WIRING=0).
Prints 'lazywire: rank=R mode=M wired=eager|lazy OK'; the lowest rank
prints 'No Errors' on success (tests/test_lazy_wiring.py greps it).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import numpy as np  # noqa: E402

from mvapich2_tpu import mpi, mpit  # noqa: E402

MODE = sys.argv[1] if len(sys.argv) > 1 else "eager"

mpi.Init()
comm = mpi.COMM_WORLD
rank, size = comm.rank, comm.size
sch = comm.u.shm_channel


def fail(msg):
    print(f"lazywire: rank={rank} FAIL {msg}", flush=True)
    mpi.Abort(comm, 1)


if MODE == "eager":
    # pre-wire eager pt2pt: no agreement required, must not block
    if sch is not None and sch._wired \
            and int(os.environ.get("MV2T_LAZY_WIRING", "1")):
        fail("channel wired before first contact")
    peer = rank ^ 1
    if peer < size:
        s = np.full(1, rank + 1, dtype=np.int32)
        r = np.zeros(1, dtype=np.int32)
        if rank < peer:
            comm.send(s, peer, tag=7)
            comm.recv(r, peer, tag=7)
        else:
            comm.recv(r, peer, tag=7)
            comm.send(s, peer, tag=7)
        if r[0] != peer + 1:
            fail(f"eager exchange got {r[0]} want {peer + 1}")
    # now force the wire through a collective
    out = np.zeros(1, dtype=np.int32)
    comm.allreduce(np.ones(1, dtype=np.int32), out)
    if out[0] != size:
        fail(f"allreduce got {out[0]} want {size}")
elif MODE == "rndv":
    n = 512 * 1024
    peer = rank ^ 1
    if peer < size:
        s = np.arange(n, dtype=np.uint8)
        s += np.uint8(rank)
        r = np.zeros(n, dtype=np.uint8)
        if rank < peer:
            comm.send(s, peer, tag=9)
            comm.recv(r, peer, tag=9)
        else:
            comm.recv(r, peer, tag=9)
            comm.send(s, peer, tag=9)
        want = np.arange(n, dtype=np.uint8)
        want += np.uint8(peer)
        if not np.array_equal(r, want):
            fail("rendezvous payload mismatch")
    out = np.zeros(1, dtype=np.int32)
    comm.allreduce(np.ones(1, dtype=np.int32), out)
elif MODE == "flat":
    out = np.zeros(1, dtype=np.int32)
    for it in range(5):
        comm.allreduce(np.full(1, rank + it, dtype=np.int32), out)
        want = sum(r + it for r in range(size))
        if out[0] != want:
            fail(f"flat allreduce iter {it} got {out[0]} want {want}")
elif MODE == "arena":
    n = (1 << 20) // 8
    s = np.full(n, float(rank + 1), dtype=np.float64)
    out = np.zeros(n, dtype=np.float64)
    comm.allreduce(s, out)
    want = float(sum(r + 1 for r in range(size)))
    if not np.allclose(out, want):
        fail(f"arena allreduce got {out[0]} want {want}")
else:
    fail(f"unknown mode {MODE}")

# observability: exactly one wire on this channel, rightly attributed
lazy = mpit.pvar("wiring_lazy").read()
eager = mpit.pvar("wiring_eager").read()
if sch is not None:
    if not sch._wired:
        fail("channel still unwired after first contact")
    expect_lazy = bool(int(os.environ.get("MV2T_LAZY_WIRING", "1")))
    if expect_lazy and not (lazy == 1 and eager == 0):
        fail(f"pvars lazy={lazy} eager={eager}, want lazy-only")
    if not expect_lazy and not (eager == 1 and lazy == 0):
        fail(f"pvars lazy={lazy} eager={eager}, want eager-only")
wired_how = "lazy" if lazy else ("eager" if eager else "none")
print(f"lazywire: rank={rank} mode={MODE} wired={wired_how} OK",
      flush=True)
mpi.Finalize()
if rank == 0:
    print("No Errors")

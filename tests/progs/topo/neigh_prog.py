"""dist_graph + neighborhood collectives (ref: topo/dgraph_adjacent,
neighb_coll)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest
from mvapich2_tpu.core import topo

comm = mtest.init()
r, s = comm.rank, comm.size

# ring as a dist graph: recv from left, send to right
left, right = (r - 1) % s, (r + 1) % s
dg = comm.dist_graph_create_adjacent([left], [right])
srcs, dsts = dg.dist_graph_neighbors()
mtest.check_eq(srcs, [left], "sources")
mtest.check_eq(dsts, [right], "destinations")

rb = np.zeros(1, np.int64)
topo.neighbor_allgather(dg, np.array([r * 5], np.int64), rb)
mtest.check_eq(rb[0], left * 5, "neighbor_allgather ring")

sb = np.array([r * 7], np.int64)
rb2 = np.zeros(1, np.int64)
topo.neighbor_alltoall(dg, sb, rb2)
mtest.check_eq(rb2[0], left * 7, "neighbor_alltoall ring")

# cart neighborhood
dims = topo.dims_create(s, 1)
cart = comm.cart_create(dims, [True])
n = cart.graph_neighbors() if cart.topo_test() == "cart" else []
rbc = np.zeros(2 * len(n) // 2 * 2, np.int64) if n else np.zeros(0)
if n:
    rbc = np.zeros(len(n), np.int64)
    topo.neighbor_allgather(cart, np.array([cart.rank], np.int64), rbc)
    mtest.check_eq(sorted(set(rbc.tolist())),
                   sorted(set(((cart.rank - 1) % s, (cart.rank + 1) % s))),
                   "cart neighbor_allgather")

mtest.finalize()

"""Cartesian topology: create/shift/sub/coords + halo sendrecv
(ref: topo/cartshift, cartsuball)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest
from mvapich2_tpu.core import topo
from mvapich2_tpu.core.status import PROC_NULL

comm = mtest.init()
r, s = comm.rank, comm.size

dims = topo.dims_create(s, 2)
cart = comm.cart_create(dims, [True, False])
mtest.check_eq(cart.topo_test(), "cart", "topo_test")
mtest.check_eq(cart.cartdim_get(), 2, "cartdim")
coords = cart.cart_coords()
mtest.check_eq(cart.cart_rank(coords), cart.rank, "coords roundtrip")

# shift in the periodic dim: always a neighbor; halo exchange
src, dst = cart.cart_shift(0, 1)
mtest.check(dst != PROC_NULL, "periodic dim has neighbor")
got = np.zeros(1, np.int64)
cart.sendrecv(np.array([cart.rank], np.int64), dst, 1, got, src, 1)
mtest.check_eq(got[0], src, "halo shift payload")

# shift in nonperiodic dim: edges get PROC_NULL
src2, dst2 = cart.cart_shift(1, 1)
d1 = dims[1]
if coords[1] == d1 - 1:
    mtest.check_eq(dst2, PROC_NULL, "edge dst PROC_NULL")
if coords[1] == 0:
    mtest.check_eq(src2, PROC_NULL, "edge src PROC_NULL")

# cart_sub: rows of the grid
row = cart.cart_sub([False, True])
mtest.check_eq(row.size, dims[1], "cart_sub size")
tot = row.allreduce(np.array([1], np.int64))
mtest.check_eq(tot[0], dims[1], "row coll")

mtest.finalize()

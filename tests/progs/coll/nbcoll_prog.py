"""Nonblocking collectives: ibarrier/ibcast/iallreduce/iallgather/ialltoall
(ref: coll/nonblocking*, sched-driven per mpid_sched.c shape)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest
from mvapich2_tpu.core import request as rq

comm = mtest.init()
r, s = comm.rank, comm.size

req = comm.ibarrier()
req.wait()

buf = np.full(9, 3.0) if r == 0 else np.zeros(9)
comm.ibcast(buf, root=0).wait()
mtest.check_eq(buf, np.full(9, 3.0), "ibcast")

sb = np.full(5, float(r + 1))
rb = np.zeros(5)
comm.iallreduce(sb, rb).wait()
mtest.check_eq(rb, np.full(5, s * (s + 1) / 2), "iallreduce")

ag = np.zeros(s, np.int64)
comm.iallgather(np.array([r * 2], np.int64), ag).wait()
mtest.check_eq(ag, np.arange(s, dtype=np.int64) * 2, "iallgather")

a2a_s = np.arange(r * s, r * s + s, dtype=np.int64)
a2a_r = np.zeros(s, np.int64)
comm.ialltoall(a2a_s, a2a_r).wait()
mtest.check_eq(a2a_r, np.arange(s, dtype=np.int64) * s + r, "ialltoall")

# several outstanding nonblocking collectives issued together
b1 = np.full(4, 1.0) if r == 0 else np.zeros(4)
b2 = np.zeros(2)
reqs = [comm.ibcast(b1, root=0), comm.iallreduce(np.full(2, 1.0), b2)]
rq.waitall(reqs)
mtest.check_eq(b1, np.full(4, 1.0), "overlapped ibcast")
mtest.check_eq(b2, np.full(2, float(s)), "overlapped iallreduce")

mtest.finalize()

"""alltoall/alltoallv with asymmetric counts (ref: coll/alltoallv*)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest

comm = mtest.init()
r, s = comm.rank, comm.size

# alltoall: rank r sends value r*s+j to rank j
sb = np.arange(r * s, r * s + s, dtype=np.int64)
rb = np.zeros(s, np.int64)
comm.alltoall(sb, rb)
mtest.check_eq(rb, np.arange(s, dtype=np.int64) * s + r, "alltoall")

# alltoallv: rank r sends (j+1) copies of r*100+j to rank j
scounts = [j + 1 for j in range(s)]
sdispls = np.concatenate([[0], np.cumsum(scounts)[:-1]]).tolist()
sbuf = np.concatenate(
    [np.full(j + 1, r * 100 + j, np.int64) for j in range(s)])
rcounts = [r + 1] * s
rdispls = [i * (r + 1) for i in range(s)]
rbuf = np.zeros(sum(rcounts), np.int64)
comm.alltoallv(sbuf, scounts, sdispls, rbuf, rcounts, rdispls)
want = np.concatenate(
    [np.full(r + 1, i * 100 + r, np.int64) for i in range(s)])
mtest.check_eq(rbuf, want, "alltoallv")

mtest.finalize()

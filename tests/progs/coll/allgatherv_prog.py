"""allgather/allgatherv uneven counts on comm variants (ref: coll/
allgatherv*)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest

comm = mtest.init()

for c, name, must_free in mtest.intracomms(comm):
    r, s = c.rank, c.size
    got = c.allgather(np.array([r, r + 100], np.int64))
    want = np.concatenate([[i, i + 100] for i in range(s)])
    mtest.check_eq(got, want, f"allgather {name}")

    counts = [2 * i + 1 for i in range(s)]
    mine = np.full(counts[r], float(r * 11))
    rv = np.zeros(sum(counts))
    c.allgatherv(mine, rv, counts)
    want = np.concatenate([np.full(counts[i], float(i * 11))
                           for i in range(s)])
    mtest.check_eq(rv, want, f"allgatherv {name}")
    if must_free:
        c.free()

mtest.finalize()

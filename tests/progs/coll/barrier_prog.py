"""Barrier ordering: a rank cannot exit before all have entered
(ref: coll/barrier variants)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import time
import numpy as np
import mtest

comm = mtest.init()
r, s = comm.rank, comm.size

for round_ in range(3):
    if r == round_ % s:
        time.sleep(0.2)     # late entrant
    t0 = time.monotonic()
    comm.barrier()
    dt = time.monotonic() - t0
    # every rank must have waited for the late one (all-but-late see >=
    # ~the sleep remaining); just verify no deadlock + data after barrier
    flag = comm.allreduce(np.array([round_], np.int64))
    mtest.check_eq(flag[0], round_ * s, f"post-barrier allreduce {round_}")
    del dt, t0

mtest.finalize()

"""gather/scatter/gatherv/scatterv (ref: coll/gather*, scatter*)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest

comm = mtest.init()
r, s = comm.rank, comm.size

# gather
out = comm.gather(np.array([r * 3, r * 3 + 1], np.int32), root=0)
if r == 0:
    mtest.check_eq(out, np.arange(2 * s, dtype=np.int32) + np.repeat(
        np.arange(s, dtype=np.int32), 2), "gather")

# scatter
sbuf = (np.arange(2 * s, dtype=np.float64) if r == 0
        else np.zeros(2 * s))
rbuf = np.zeros(2)
comm.scatter(sbuf, rbuf, root=0)
mtest.check_eq(rbuf, np.array([2 * r, 2 * r + 1], np.float64), "scatter")

# gatherv: rank i contributes i+1 elements
counts = [i + 1 for i in range(s)]
total = sum(counts)
mine = np.full(r + 1, float(r), np.float64)
rv = np.zeros(total) if r == 0 else np.zeros(total)
comm.gatherv(mine, rv, counts, root=0)
if r == 0:
    want = np.concatenate([np.full(i + 1, float(i)) for i in range(s)])
    mtest.check_eq(rv, want, "gatherv")

# scatterv with displacements (reversed layout)
displs = [total - sum(counts[: i + 1]) for i in range(s)]
sv = np.arange(total, dtype=np.float64) if r == 0 else np.zeros(total)
rsv = np.zeros(counts[r])
comm.scatterv(sv, counts, displs, rsv, root=0)
mtest.check_eq(rsv, np.arange(total, dtype=np.float64)[
    displs[r]: displs[r] + counts[r]], "scatterv")

mtest.finalize()

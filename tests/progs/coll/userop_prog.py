"""User-defined ops: commutative and non-commutative reduction order
(ref: coll/op_commutative, opband-style)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest
from mvapich2_tpu.core.op import create_op

comm = mtest.init()
r, s = comm.rank, comm.size

# commutative user op: elementwise hypot
hyp = create_op(lambda a, b: np.sqrt(a * a + b * b), commute=True,
                name="hypot")
out = comm.allreduce(np.full(4, 3.0), op=hyp)
mtest.check(np.allclose(out, np.full(4, 3.0 * np.sqrt(s))), "hypot")

# non-commutative user op: 2x2 matrix multiply in rank order encoded as
# flat vec [a,b,c,d]; result must be M_0 @ M_1 @ ... @ M_{s-1}
def matmul2(invec, inout):
    a = invec.reshape(2, 2)
    b = inout.reshape(2, 2)
    return (a @ b).reshape(-1)

mm = create_op(matmul2, commute=False, name="matmul2")
mine = np.array([1.0, float(r + 1), 0.0, 1.0])
got = comm.allreduce(mine, op=mm)
want = np.array([1.0, sum(range(1, s + 1)), 0.0, 1.0])
mtest.check(np.allclose(got, want), f"noncommutative order: {got}")

mtest.finalize()

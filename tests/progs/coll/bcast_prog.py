"""bcast over roots/sizes/dtypes and comm variants (ref: coll/bcasttest)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest

comm = mtest.init()

for c, name, must_free in mtest.intracomms(comm):
    for root in range(min(c.size, 3)):
        for n in (1, 33, 4096):
            buf = (np.arange(n, dtype=np.float64) * (root + 2)
                   if c.rank == root else np.zeros(n))
            c.bcast(buf, root=root)
            mtest.check_eq(buf, np.arange(n, dtype=np.float64) * (root + 2),
                           f"bcast {name} root={root} n={n}")
    ibuf = np.full(7, c.rank, np.int32)
    if c.rank == 0:
        ibuf[:] = 42
    c.bcast(ibuf, root=0)
    mtest.check_eq(ibuf, np.full(7, 42, np.int32), f"bcast int {name}")
    if must_free:
        c.free()

mtest.finalize()

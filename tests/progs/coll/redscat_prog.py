"""reduce_scatter_block + scan/exscan (ref: coll/redscat*, scantst)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest
from mvapich2_tpu.core import op as ops

comm = mtest.init()
r, s = comm.rank, comm.size
B = 4

sb = np.arange(s * B, dtype=np.float64) + r
rb = np.zeros(B)
comm.reduce_scatter_block(sb, rb)
want = (np.arange(r * B, (r + 1) * B, dtype=np.float64) * s
        + s * (s - 1) / 2)
mtest.check_eq(rb, want, "reduce_scatter_block")

sc = comm.scan(np.full(3, float(r + 1)))
mtest.check_eq(sc, np.full(3, sum(range(1, r + 2)), np.float64), "scan")

ex = comm.exscan(np.full(3, float(r + 1)))
if r > 0:
    mtest.check_eq(ex, np.full(3, sum(range(1, r + 1)), np.float64),
                   "exscan")

mx = comm.scan(np.array([float(r)]), op=ops.MAX)
mtest.check_eq(mx[0], float(r), "scan max")

mtest.finalize()

"""reduce with builtin ops incl. MINLOC/MAXLOC (ref: coll/red*, minmaxloc)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest
from mvapich2_tpu.core import op as ops

comm = mtest.init()
r, s = comm.rank, comm.size

for root in range(min(s, 2)):
    data = np.arange(10, dtype=np.float64) + r
    out = comm.reduce(data, op=ops.SUM, root=root)
    if r == root:
        want = np.arange(10, dtype=np.float64) * s + s * (s - 1) / 2
        mtest.check_eq(out, want, f"reduce sum root={root}")
    out = comm.reduce(data, op=ops.MAX, root=root)
    if r == root:
        mtest.check_eq(out, np.arange(10, dtype=np.float64) + s - 1,
                       "reduce max")
    out = comm.reduce(data, op=ops.MIN, root=root)
    if r == root:
        mtest.check_eq(out, np.arange(10, dtype=np.float64), "reduce min")

prod = comm.reduce(np.full(3, 2.0), op=ops.PROD, root=0)
if r == 0:
    mtest.check_eq(prod, np.full(3, 2.0 ** s), "reduce prod")

# logical/bitwise
lv = comm.allreduce(np.array([r % 2, 1], np.int32), op=ops.LAND)
mtest.check_eq(lv, np.array([1 if s == 1 else 0, 1], np.int32), "land")
bv = comm.allreduce(np.array([1 << r], np.int64), op=ops.BOR)
mtest.check_eq(bv[0], (1 << s) - 1, "bor")

# MINLOC/MAXLOC on (val, loc) structured vectors
from mvapich2_tpu.core import datatype as dt
pair = np.zeros(2, dtype=dt.DOUBLE_INT.basic)
pair["val"] = [float((r + 1) % s), float(s - r)]
pair["loc"] = r
mn = comm.allreduce(pair, op=ops.MINLOC, datatype=dt.DOUBLE_INT, count=2)
mtest.check_eq(mn["val"][0], 0.0, "minloc val")
mtest.check_eq(mn["loc"][0], s - 1, "minloc loc")
mx = comm.allreduce(pair, op=ops.MAXLOC, datatype=dt.DOUBLE_INT, count=2)
mtest.check_eq(mx["val"][1], float(s), "maxloc val")
mtest.check_eq(mx["loc"][1], 0, "maxloc loc")

mtest.finalize()

"""RMA put/get under fence epochs (ref: rma/putfence1, getfence1)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest

comm = mtest.init()
r, s = comm.rank, comm.size

buf = np.full(8, float(r), np.float64)
win = comm.win_create(buf, disp_unit=8)

win.fence()
# everyone puts its rank into slot r of the right neighbor
win.put(np.array([float(r * 10)]), (r + 1) % s, target_disp=r % 8)
win.fence()
mtest.check_eq(buf[(r - 1) % s % 8], float(((r - 1) % s) * 10),
               "put landed")

# get from left neighbor
got = np.zeros(2)
win.fence()
win.get(got, (r - 1) % s, target_disp=0, count=2)
win.fence()
left = (r - 1) % s
ll = (left - 1) % s        # the rank that put into `left`'s window
want0 = float(ll * 10) if ll % 8 == 0 else float(left)
mtest.check_eq(got[0], want0 if s > 1 else float(r * 10), "get value")

win.free()
mtest.finalize()

"""accumulate/get_accumulate/fetch_and_op/compare_and_swap under locks
(ref: rma/accfence1, fetchandadd, compare_and_swap)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest
from mvapich2_tpu.core import op as ops
from mvapich2_tpu.rma.win import LOCK_EXCLUSIVE, LOCK_SHARED

comm = mtest.init()
r, s = comm.rank, comm.size

buf = np.zeros(4, np.int64)
win = comm.win_create(buf, disp_unit=8)

# every rank accumulates 1+r into slot 0 of rank 0 — sum must be exact
win.fence()
win.accumulate(np.array([1 + r], np.int64), 0, target_disp=0, op=ops.SUM)
win.fence()
if r == 0:
    mtest.check_eq(buf[0], s * (s + 1) // 2, "accumulate sum")

# fetch_and_op: atomic counter on rank 0 slot 1
res = np.zeros(1, np.int64)
win.lock(0, LOCK_SHARED)
win.fetch_and_op(np.array([1], np.int64), res, 0, target_disp=1,
                 op=ops.SUM)
win.unlock(0)
comm.barrier()
if r == 0:
    mtest.check_eq(buf[1], s, "fetch_and_op total")
vals = comm.allgather(res)
mtest.check_eq(sorted(vals.tolist()), list(range(s)),
               "fetch_and_op tickets unique")

# compare_and_swap: only one rank wins the swap on slot 2
winner = np.zeros(1, np.int64)
win.lock(0, LOCK_EXCLUSIVE)
win.compare_and_swap(np.array([r + 1], np.int64),
                     np.array([0], np.int64), winner, 0, target_disp=2)
win.unlock(0)
comm.barrier()
nwin = comm.allreduce(np.array([1 if winner[0] == 0 else 0], np.int64))
mtest.check_eq(nwin[0], 1, "exactly one CAS winner")

# get_accumulate with NO_OP = atomic read
snap = np.zeros(1, np.int64)
win.lock(0, LOCK_SHARED)
win.get_accumulate(np.array([0], np.int64), snap, 0, target_disp=0,
                   op=ops.NO_OP)
win.unlock(0)
mtest.check_eq(snap[0], s * (s + 1) // 2, "get_accumulate NO_OP read")

win.free()
mtest.finalize()

"""PSCW generalized active target sync (ref: rma/test2, post/start/
complete/wait patterns)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest
from mvapich2_tpu.core.group import Group

comm = mtest.init()
r, s = comm.rank, comm.size

if s >= 2:
    buf = np.zeros(4, np.float64)
    win = comm.win_create(buf, disp_unit=8)
    origin_g = Group([0])
    target_g = Group([1])

    if r == 1:
        win.post(origin_g)
        win.wait()
        mtest.check_eq(buf, np.array([5.0, 6.0, 0.0, 0.0]), "pscw payload")
    elif r == 0:
        win.start(target_g)
        win.put(np.array([5.0, 6.0]), 1, target_disp=0)
        win.complete()

    comm.barrier()
    win.free()

mtest.finalize()

"""Dynamic windows: attach/detach + win_allocate (ref: rma/win_dynamic_acc,
winallocate)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest

comm = mtest.init()
r, s = comm.rank, comm.size

# win_allocate: library-provided buffer, exposed as win.base
win = comm.win_allocate(8 * 8, disp_unit=8)
local = win.base.view(np.float64)
local[:] = r
win.fence()
win.put(np.full(2, float(100 + r)), (r + 1) % s, 2)
win.fence()
mtest.check_eq(local[2], float(100 + (r - 1) % s), "allocate+put")
win.free()

# dynamic: attach a region, exchange absolute addresses, put into it
dwin = comm.win_create_dynamic()
region = np.zeros(16, np.float64)
addr = dwin.attach(region)
addrs = np.zeros(s, np.int64)
comm.allgather(np.array([addr], np.int64), addrs, count=1)
dwin.fence()
t = (r + 1) % s
dwin.put(np.array([float(r + 1)]), t, int(addrs[t]) + 8 * (r % 16))
dwin.fence()
left = (r - 1) % s
mtest.check_eq(region[left % 16], float(left + 1), "dynamic put")
dwin.detach(addr)
dwin.free()

mtest.finalize()

"""Passive target: lock_all/flush/unlock_all, rput/rget request forms
(ref: rma/lockall_dt, rput variants)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest

comm = mtest.init()
r, s = comm.rank, comm.size

buf = np.zeros(s, np.float64)
win = comm.win_create(buf, disp_unit=8)

win.lock_all()
# scatter my rank into everyone's slot r
reqs = [win.rput(np.array([float(r + 100)]), t, target_disp=r)
        for t in range(s)]
for q in reqs:
    q.wait()
win.flush_all()
comm.barrier()          # all puts flushed everywhere
got = np.zeros(s)
win.rget(got, r, count=s).wait()
win.unlock_all()
mtest.check_eq(got, np.arange(s, dtype=np.float64) + 100,
               "lock_all rput/rget")

win.free()
mtest.finalize()

"""mtest — shared harness for conformance programs.

Analog of the reference suite's test/mpi/util/mtest.c:34-80: init/finalize
wrappers, communicator iterators, error accounting, and the exact
"No Errors" success contract checked by bin/runtests (runtests.in shape).

Programs do:

    import mtest
    comm = mtest.init()
    ...mtest.check(cond, "msg")...
    mtest.finalize()          # prints 'No Errors' on rank 0 iff no rank
                              # recorded an error; exits nonzero otherwise
"""

import sys

sys.path.insert(0, ".")

import numpy as np  # noqa: E402

from mvapich2_tpu import mpi  # noqa: E402

_errs = 0


def error(msg: str) -> None:
    global _errs
    _errs += 1
    r = mpi.COMM_WORLD.rank if mpi.Initialized() else -1
    print(f"rank {r}: ERROR: {msg}", file=sys.stderr, flush=True)


def check(cond, msg: str) -> bool:
    if not cond:
        error(msg)
    return bool(cond)


def check_eq(got, want, msg: str) -> bool:
    ok = np.array_equal(np.asarray(got), np.asarray(want))
    if not ok:
        error(f"{msg}: got {got!r} want {want!r}")
    return ok


def init(required: int = mpi.THREAD_SINGLE):
    mpi.Init(required)
    return mpi.COMM_WORLD


def intracomms(comm):
    """Communicator iterator (MTestGetIntracomm shape): yields (comm,
    name, must_free) variants — world, dup, reversed-rank split, and the
    even/odd halves when size allows."""
    yield comm, "world", False
    yield comm.dup(), "dup", True
    yield comm.split(0, comm.size - comm.rank), "rev", True
    if comm.size >= 4:
        yield comm.split(comm.rank % 2, comm.rank), "halves", True


def finalize() -> None:
    comm = mpi.COMM_WORLD
    tot = int(comm.allreduce(np.array([_errs], np.int64))[0])
    if comm.rank == 0 and tot == 0:
        print("No Errors")
    mpi.Finalize()
    sys.exit(1 if tot else 0)

"""Attributes on windows and datatypes + datatype envelope introspection
(ref: attr/fkeyval{win,type}, datatype/contents)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest
from mvapich2_tpu.core import datatype as dt
from mvapich2_tpu.core.attr import Keyval

comm = mtest.init()
r, s = comm.rank, comm.size

# window attributes with a delete callback fired at free
deleted = []
kv = Keyval(delete_fn=lambda obj, k, val, extra: deleted.append(val))
buf = np.zeros(4, np.float64)
win = comm.win_create(buf, disp_unit=8)
win.attrs.set(win, kv, 5 + r)
found, val = win.attrs.get(kv)
mtest.check(found and val == 5 + r, "win attr set/get")
win.free()
mtest.check_eq(deleted, [5 + r], "win attr delete_fn at free")

# datatype attributes
vec = dt.create_vector(3, 1, 2, dt.DOUBLE).commit()
kv2 = Keyval()
vec.attrs.set(vec, kv2, "tagged")
found, val = vec.attrs.get(kv2)
mtest.check(found and val == "tagged", "type attr set/get")

# envelope introspection: constructor call reconstructable
comb, ints, aints, types = vec.get_envelope()
mtest.check_eq(comb, "vector", "vector combiner")
mtest.check_eq(ints, [3, 1, 2], "vector ints")
mtest.check_eq(types[0].name, "MPI_DOUBLE", "vector oldtype")

sub = dt.create_subarray([4, 6], [2, 3], [1, 2], dt.INT)
comb, ints, _, _ = sub.get_envelope()
mtest.check_eq(comb, "subarray", "subarray combiner")
mtest.check_eq(ints, [2, 4, 6, 2, 3, 1, 2, 0],
               "subarray ints (orig order + order flag)")

st_dt = dt.create_struct([1, 2], [0, 8], [dt.INT, dt.DOUBLE])
comb, ints, aints, types = st_dt.get_envelope()
mtest.check_eq(comb, "struct", "struct combiner")
mtest.check_eq(aints, [0, 8], "struct displacements")
mtest.check_eq(len(types), 2, "struct types")

mtest.check_eq(dt.DOUBLE.get_envelope()[0], "named", "basic = named")

mtest.finalize()

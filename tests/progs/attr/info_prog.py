"""Info objects: set/get/delete/dup/nkeys (ref: info/infotest, infodup)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import mtest
from mvapich2_tpu.core.info import Info

comm = mtest.init()

info = Info()
info.set("file", "runfile.txt")
info.set("soft", "2:4")
mtest.check_eq(info.nkeys, 2, "nkeys")
mtest.check_eq(info.get("file"), "runfile.txt", "get")
mtest.check(info.get("missing") is None, "missing key")

d = info.dup()
d.set("wdir", "/tmp")
mtest.check_eq(d.nkeys, 3, "dup nkeys")
mtest.check_eq(info.nkeys, 2, "dup isolation")

info.delete("soft")
mtest.check_eq(info.nkeys, 1, "delete")
keys = [d.nthkey(i) for i in range(d.nkeys)]
mtest.check("wdir" in keys and "file" in keys, "nthkey enumeration")

mtest.finalize()

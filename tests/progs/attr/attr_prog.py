"""Attribute caching: keyvals, copy-on-dup, delete callbacks
(ref: attr/attrt, attrdeleteget, fkeyvalcomm)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import mtest
from mvapich2_tpu.core.attr import Keyval

comm = mtest.init()

deleted = []
kv = Keyval(copy_fn=lambda obj, k, extra, val: (True, val * 2),
            delete_fn=lambda obj, k, val, extra: deleted.append(val))
comm.attrs.set(comm, kv, 10)
found, val = comm.attrs.get(kv)
mtest.check(found and val == 10, "set/get")

dup = comm.dup()
found, val = dup.attrs.get(kv)
mtest.check(found and val == 20, "copy_fn applied on dup")

dup.attrs.delete(dup, kv)
mtest.check_eq(deleted, [20], "delete_fn called")
found, _ = dup.attrs.get(kv)
mtest.check(not found, "deleted attr gone")
dup.free()

# no-copy keyval: attribute does not propagate to dup
kv2 = Keyval()
comm.attrs.set(comm, kv2, "x")
d2 = comm.dup()
found, _ = d2.attrs.get(kv2)
mtest.check(not found, "default keyval not copied")
d2.free()

found, val = comm.attrs.get(kv)
mtest.check(found and val == 10, "original untouched")

mtest.finalize()

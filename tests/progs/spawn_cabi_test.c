/* spawn_cabi_test — MPI_Comm_spawn / get_parent / disconnect through
 * the C ABI (native/mpi/libmpi_ext.c dynamic-process surface over
 * runtime/spawn.py). Parent spawns 2 copies of itself (argv[0]), sends
 * each child its rank, children echo via the parent intercomm. */
#include <mpi.h>
#include <stdio.h>
#include <string.h>

int main(int argc, char *argv[])
{
    int errs = 0, rank, i;
    MPI_Comm parent, inter;
    int errcodes[2];

    MPI_Init(&argc, &argv);
    MPI_Comm_get_parent(&parent);

    if (parent == MPI_COMM_NULL) {
        int rsize, echoed;
        MPI_Comm_rank(MPI_COMM_WORLD, &rank);
        if (MPI_Comm_spawn(argv[0], MPI_ARGV_NULL, 2, MPI_INFO_NULL, 0,
                           MPI_COMM_WORLD, &inter, errcodes)
            != MPI_SUCCESS) {
            printf("spawn failed\n");
            MPI_Abort(MPI_COMM_WORLD, 1);
        }
        for (i = 0; i < 2; i++)
            if (errcodes[i] != MPI_SUCCESS)
                errs++;
        MPI_Comm_remote_size(inter, &rsize);
        if (rsize != 2) {
            printf("remote size %d != 2\n", rsize);
            errs++;
        }
        for (i = 0; i < 2; i++) {
            MPI_Send(&i, 1, MPI_INT, i, 7, inter);
            MPI_Recv(&echoed, 1, MPI_INT, i, 8, inter,
                     MPI_STATUS_IGNORE);
            if (echoed != i * 10) {
                printf("child %d echoed %d\n", i, echoed);
                errs++;
            }
        }
        MPI_Comm_disconnect(&inter);
        if (errs == 0)
            printf(" No Errors\n");
        else
            printf(" Found %d errors\n", errs);
    } else {
        int got, reply;
        char cname[MPI_MAX_OBJECT_NAME];
        int rlen = 0;
        MPI_Comm_rank(MPI_COMM_WORLD, &rank);
        MPI_Comm_get_name(parent, cname, &rlen);
        if (strcmp(cname, "MPI_COMM_PARENT") != 0)
            fprintf(stderr, "child: bad parent name %s\n", cname);
        MPI_Recv(&got, 1, MPI_INT, 0, 7, parent, MPI_STATUS_IGNORE);
        reply = got * 10;
        MPI_Send(&reply, 1, MPI_INT, 0, 8, parent);
        MPI_Comm_disconnect(&parent);
    }
    MPI_Finalize();
    return 0;
}

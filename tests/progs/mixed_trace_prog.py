"""Mixed-ABI tracing workload (ISSUE 10): rank dispatcher.

Launched as the program of an ``-np 4`` job. EVEN ranks exec the
compiled C binary (argv[1] — tests/progs/ntrace_cabi_test.c built with
bin/mpicc), becoming genuine C-ABI processes whose MPI calls never
cross the interpreter; ODD ranks run the IDENTICAL workload through the
python API. Under bin/mpitrace every rank — both ABIs — dumps ONE
trace file at Finalize, and the merge must show the native C-plane
events (flat waves, doorbells, eager hops) time-aligned with the
python ranks' mpi-layer spans.

    bin/mpitrace -np 4 --out m.json python tests/progs/mixed_trace_prog.py <cbin>
"""

import os
import sys

rank = int(os.environ.get("MV2T_RANK", "0"))
cbin = sys.argv[1]

if rank % 2 == 0:
    # become a real C-ABI process (env — MV2T_*, MV2T_TRACE* — rides
    # along; the exec'd binary bootstraps through libmpi.so)
    os.execv(cbin, [cbin])

# -- python half: the same sequence as ntrace_cabi_test.c ---------------
sys.path.insert(0, ".")
import numpy as np  # noqa: E402

from mvapich2_tpu import mpi  # noqa: E402

N, PP, REPS = 16, 64, 3

mpi.Init()
comm = mpi.COMM_WORLD
me, np_ = comm.rank, comm.size
errs = 0

comm.barrier()

for rep in range(REPS):
    sb = np.full(N, 1 + rep, np.int32)
    rb = comm.allreduce(sb)
    if not (rb == np_ * (1 + rep)).all():
        errs += 1

if (me ^ 1) < np_:
    peer = me ^ 1
    pb = (me * 1000 + np.arange(PP)).astype(np.int32)
    qb = np.zeros(PP, np.int32)
    if me % 2 == 0:
        comm.send(pb, dest=peer, tag=7)
        comm.recv(qb, source=peer, tag=7)
    else:
        comm.recv(qb, source=peer, tag=7)
        comm.send(pb, dest=peer, tag=7)
    if not (qb == peer * 1000 + np.arange(PP)).all():
        errs += 1

comm.barrier()

total = comm.allreduce(np.array([errs], np.int32))
if me == 0 and int(total[0]) == 0:
    print("No Errors")
mpi.Finalize()
sys.exit(1 if errs else 0)

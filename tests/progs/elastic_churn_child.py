"""One elastic session world: join, one exchange with the resident
world, leave. Spawned per cycle by elastic_churn_prog.py. Intercomm
allreduce semantics: each side receives the OTHER group's reduction —
the session contributes 1000, and receives the resident ranks' sum."""

import sys

sys.path.insert(0, ".")

import numpy as np  # noqa: E402

from mvapich2_tpu import mpi  # noqa: E402

mpi.Init()
parent = mpi.Comm_get_parent()
assert parent is not None and parent.is_inter, "no parent intercomm"

got = parent.allreduce(np.array([1000], dtype=np.int64))
assert int(got[0]) == sum(range(parent.remote_size)), got
parent.disconnect()
mpi.Finalize()
sys.exit(0)

/* Chaos through the C ABI: small allreduces loop on the flat-slot tier
 * (fastpath.c -> cp_flat_*) while the NATIVE fault engine (MV2T_FAULTS
 * flat_fold@<victim>:crash:...) kills one rank mid-wave. Survivors run
 * with MPI_ERRORS_RETURN and must see MPIX_ERR_PROC_FAILED (lease
 * detection inside the C flat wait — no launcher watcher), then
 * revoke + shrink and finish a collective on the shrunken comm.
 *
 * Run: mpirun -np N  (MPIEXEC_ALLOW_FAULT=1, MV2T_FT_WATCHER=0,
 *      MV2T_PEER_TIMEOUT=<small>)               prints "No Errors". */
#include <mpi.h>
#include <stdio.h>

int main(void) {
    MPI_Init(NULL, NULL);
    MPI_Errhandler_set(MPI_COMM_WORLD, MPI_ERRORS_RETURN);
    int rank, size;
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);

    int err = MPI_SUCCESS;
    for (int i = 0; i < 500; i++) {
        int s = rank + 1, r = 0;
        int rc = MPI_Allreduce(&s, &r, 1, MPI_INT, MPI_SUM,
                               MPI_COMM_WORLD);
        if (rc != MPI_SUCCESS) {
            err = rc;
            break;
        }
        if (r != size * (size + 1) / 2) {
            printf("rank %d: corrupt allreduce %d\n", rank, r);
            fflush(stdout);
            MPI_Abort(MPI_COMM_WORLD, 2);
        }
    }
    if (err == MPI_SUCCESS) {
        /* the victim never gets here (it crashed); a survivor that saw
         * no error means containment failed to surface */
        printf("rank %d: fault never surfaced\n", rank);
        fflush(stdout);
        MPI_Abort(MPI_COMM_WORLD, 3);
    }
    int cls = 0;
    MPI_Error_class(err, &cls);
    if (cls != MPIX_ERR_PROC_FAILED && cls != MPIX_ERR_REVOKED) {
        printf("rank %d: unexpected error class %d\n", rank, cls);
        fflush(stdout);
        MPI_Abort(MPI_COMM_WORLD, 4);
    }

    MPIX_Comm_revoke(MPI_COMM_WORLD);
    MPIX_Comm_failure_ack(MPI_COMM_WORLD);
    MPI_Comm small;
    if (MPIX_Comm_shrink(MPI_COMM_WORLD, &small) != MPI_SUCCESS) {
        printf("rank %d: shrink failed\n", rank);
        fflush(stdout);
        MPI_Abort(MPI_COMM_WORLD, 5);
    }
    int nsz, nrank, s = 1, r = 0;
    MPI_Comm_size(small, &nsz);
    MPI_Comm_rank(small, &nrank);
    if (MPI_Allreduce(&s, &r, 1, MPI_INT, MPI_SUM, small)
            != MPI_SUCCESS || r != nsz) {
        printf("rank %d: shrunken allreduce wrong (%d/%d)\n", rank, r,
               nsz);
        fflush(stdout);
        MPI_Abort(MPI_COMM_WORLD, 6);
    }
    if (nrank == 0) {
        printf("chaos-cabi: err_class=%d shrunk=%d\n", cls, nsz);
        printf("No Errors\n");
    }
    fflush(stdout);
    MPI_Comm_free(&small);
    MPI_Finalize();
    return 0;
}

"""metrics live-scrape target (ISSUE 17): a job that prints its shm
segment stem (``SEG <path>``, from the lowest python rank) and then
runs collectives long enough for an external bin/mpimetrics /
bin/mpistat to scrape live telemetry from the metrics ring. Prints
"No Errors" on clean completion — the scrape must not have perturbed
the job.

Two modes:

  python tests/progs/metrics_target_prog.py
      All ranks python. The loop mixes flat-tier allreduces (small,
      contiguous) with periodic sched-tier allreduces (64 KiB — over
      the flat-region byte cap, so the schedule path runs and its
      rendezvous pt2pt traffic exercises the chunk-latency histogram).
      Duration: MV2T_TEST_STAT_SECONDS (default 6).

  python tests/progs/metrics_target_prog.py <cbin>
      Mixed-ABI: EVEN ranks exec the compiled ntrace_cabi_test binary;
      ODD ranks run the IDENTICAL C sequence through the python API so
      the collectives stay balanced across the ABI boundary. Pace the
      shared workload with MV2T_TEST_CABI_REPS / MV2T_TEST_CABI_USLEEP
      (read by both halves).

Launched via: python -m mvapich2_tpu.run -np 4 python tests/progs/metrics_target_prog.py [cbin]
"""

import os
import sys
import time

rank = int(os.environ.get("MV2T_RANK", "0"))
cbin = sys.argv[1] if len(sys.argv) > 1 else None

if cbin is not None and rank % 2 == 0:
    # become a real C-ABI process (env — MV2T_METRICS et al — rides
    # along; the exec'd binary bootstraps through libmpi.so)
    os.execv(cbin, [cbin])

sys.path.insert(0, ".")
import numpy as np  # noqa: E402

from mvapich2_tpu import mpi  # noqa: E402

mpi.Init()
comm = mpi.COMM_WORLD
me, np_ = comm.rank, comm.size
errs = 0

# the lowest python rank announces the segment stem for the scraper
lowest_py = 1 if cbin is not None else 0
sch = comm.u.shm_channel
if me == lowest_py:
    print(f"SEG {sch.path if sch is not None else '-'}", flush=True)

if cbin is None:
    # -- all-python half: flat + sched tiers, fixed iteration count ----
    # (NOT a wall-clock deadline: collectives must be issued the same
    # number of times on every rank)
    iters = int(float(os.environ.get("MV2T_TEST_STAT_SECONDS", "6"))
                / 0.01)
    small = np.ones(16, np.float64)
    big = np.ones(8192, np.float64)          # 64 KiB: sched tier
    comm.barrier()
    for i in range(iters):
        out = comm.allreduce(small)
        if out[0] != np_:
            errs += 1
        if i % 8 == 0:
            out = comm.allreduce(big)
            if out[0] != np_:
                errs += 1
        time.sleep(0.005)
else:
    # -- python half of the mixed job: ntrace_cabi_test.c's sequence --
    N, PP = 16, 64
    reps = int(os.environ.get("MV2T_TEST_CABI_REPS", "3"))
    pause = int(os.environ.get("MV2T_TEST_CABI_USLEEP", "0")) / 1e6
    comm.barrier()
    for rep in range(reps):
        sb = np.full(N, 1 + rep, np.int32)
        rb = comm.allreduce(sb)
        if not (rb == np_ * (1 + rep)).all():
            errs += 1
        if pause:
            time.sleep(pause)
    if (me ^ 1) < np_:
        peer = me ^ 1
        pb = (me * 1000 + np.arange(PP)).astype(np.int32)
        qb = np.zeros(PP, np.int32)
        if me % 2 == 0:
            comm.send(pb, dest=peer, tag=7)
            comm.recv(qb, source=peer, tag=7)
        else:
            comm.recv(qb, source=peer, tag=7)
            comm.send(pb, dest=peer, tag=7)
        if not (qb == peer * 1000 + np.arange(PP)).all():
            errs += 1

comm.barrier()
total = comm.allreduce(np.array([errs], np.int32))
if me == lowest_py and int(total[0]) == 0:
    print("No Errors")
mpi.Finalize()
sys.exit(1 if errs else 0)

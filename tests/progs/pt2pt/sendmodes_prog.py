"""Send modes: ssend/issend/bsend/rsend semantics (ref: pt2pt/*send*).

rsend note: ready mode is treated as standard mode (an implementation is
permitted to do so; erroneous-usage detection is intentionally dropped —
see core/comm.py rsend).
"""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest

comm = mtest.init()
r, s = comm.rank, comm.size

if s >= 2 and r < 2:
    peer = 1 - r
    # issend completes only after the receive is posted
    req = comm.issend(np.full(4, 7.0 + r), peer, tag=1)
    got = np.zeros(4)
    comm.recv(got, peer, tag=1)
    req.wait()
    mtest.check_eq(got, np.full(4, 7.0 + peer), "issend payload")

    # bsend returns immediately (buffered), recv later
    comm.bsend(np.arange(5, dtype=np.int32) * (r + 1), peer, tag=2)
    got2 = np.zeros(5, np.int32)
    comm.recv(got2, peer, tag=2)
    mtest.check_eq(got2, np.arange(5, dtype=np.int32) * (peer + 1),
                   "bsend payload")

    # rsend (as-standard semantics)
    if r == 0:
        got3 = np.zeros(3, np.int64)
        comm.recv(got3, 1, tag=3)
        mtest.check_eq(got3, np.array([9, 9, 9], np.int64), "rsend payload")
    else:
        comm.rsend(np.array([9, 9, 9], np.int64), 0, tag=3)

    # ssend blocking form
    if r == 0:
        comm.ssend(np.array([1.5]), 1, tag=4)
    else:
        g = np.zeros(1)
        comm.recv(g, 0, tag=4)
        mtest.check_eq(g[0], 1.5, "ssend payload")

comm.barrier()
mtest.finalize()

"""probe/iprobe/improbe/mrecv + status fields (ref: pt2pt/probe*, mprobe)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest
from mvapich2_tpu.core.status import ANY_SOURCE, ANY_TAG

comm = mtest.init()
r, s = comm.rank, comm.size

if s >= 2:
    if r == 0:
        comm.send(np.arange(7, dtype=np.float64), 1, tag=3)
        comm.send(np.arange(9, dtype=np.int32), 1, tag=4)
    elif r == 1:
        st = comm.probe(source=0, tag=3)
        mtest.check_eq(st.source, 0, "probe source")
        mtest.check_eq(st.tag, 3, "probe tag")
        mtest.check_eq(st.count, 7 * 8, "probe count")
        buf = np.zeros(7, np.float64)
        comm.recv(buf, 0, 3)
        mtest.check_eq(buf, np.arange(7, dtype=np.float64), "probed payload")

        # improbe + mrecv: matched message removed from matching
        msg = None
        while msg is None:
            msg = comm.improbe(ANY_SOURCE, ANY_TAG)
        buf2 = np.zeros(9, np.int32)
        st2 = comm.mrecv(msg, buf2)
        mtest.check_eq(st2.tag, 4, "mrecv tag")
        mtest.check_eq(buf2, np.arange(9, dtype=np.int32), "mrecv payload")

        # iprobe on empty queue returns None
        mtest.check(comm.iprobe(source=0, tag=99) is None,
                    "iprobe matched nonexistent message")

mtest.finalize()

"""waitall/waitany/waitsome/testall over request batches (ref: pt2pt/wait*)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest
from mvapich2_tpu.core import request as rq

comm = mtest.init()
r, s = comm.rank, comm.size
N = 8

if s >= 2 and r < 2:
    peer = 1 - r
    recvs = [np.zeros(4, np.int32) for _ in range(N)]
    rr = [comm.irecv(recvs[i], peer, tag=i) for i in range(N)]
    sr = [comm.isend(np.full(4, 100 * r + i, np.int32), peer, tag=i)
          for i in range(N)]

    # waitany drains one at a time
    done = set()
    pending = list(rr)
    while pending:
        idx = rq.waitany(pending)
        done.add(id(pending[idx]))
        pending = [q for j, q in enumerate(pending) if j != idx]
    mtest.check_eq(len(done), N, "waitany drained all recvs")
    rq.waitall(sr)
    for i in range(N):
        mtest.check_eq(recvs[i], np.full(4, 100 * peer + i, np.int32),
                       f"payload {i}")

    # testall on fresh batch
    recvs2 = [np.zeros(2, np.int32) for _ in range(N)]
    rr2 = [comm.irecv(recvs2[i], peer, tag=50 + i) for i in range(N)]
    sr2 = [comm.isend(np.full(2, i, np.int32), peer, tag=50 + i)
           for i in range(N)]
    while not rq.testall(rr2):
        pass
    rq.waitall(sr2)
    for i in range(N):
        mtest.check_eq(recvs2[i], np.full(2, i, np.int32), f"payload2 {i}")

    # waitsome returns a nonempty batch
    recvs3 = [np.zeros(1, np.int32) for _ in range(4)]
    rr3 = [comm.irecv(recvs3[i], peer, tag=80 + i) for i in range(4)]
    sr3 = [comm.isend(np.array([i], np.int32), peer, tag=80 + i)
           for i in range(4)]
    remaining = list(rr3)
    while remaining:
        idxs = rq.waitsome(remaining)
        mtest.check(len(idxs) >= 1, "waitsome empty batch")
        remaining = [q for j, q in enumerate(remaining)
                     if j not in set(idxs)]
    rq.waitall(sr3)

comm.barrier()
mtest.finalize()

"""Wildcard source/tag matching + status interrogation (ref: pt2pt/anyall,
status/*)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest
from mvapich2_tpu.core.status import ANY_SOURCE, ANY_TAG

comm = mtest.init()
r, s = comm.rank, comm.size

if r == 0:
    seen = set()
    for _ in range(s - 1):
        buf = np.zeros(2, np.int64)
        st = comm.recv(buf, ANY_SOURCE, ANY_TAG)
        mtest.check_eq(st.source, buf[0], "status.source vs payload")
        mtest.check_eq(st.tag, 10 + buf[0], "status.tag vs payload")
        mtest.check_eq(st.count, 16, "status count")
        seen.add(int(buf[0]))
    mtest.check_eq(sorted(seen), list(range(1, s)), "all senders seen")
else:
    comm.send(np.array([r, r * r], np.int64), 0, tag=10 + r)

mtest.finalize()

"""pt2pt basics across dtypes/tags (ref suite pattern: pt2pt/sendrecv*)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest

comm = mtest.init()
r, s = comm.rank, comm.size

for dt in (np.int32, np.int64, np.float32, np.float64, np.uint8):
    mine = np.arange(16, dtype=dt) + dt(r)
    got = np.zeros(16, dt)
    comm.sendrecv(mine, (r + 1) % s, 5, got, (r - 1) % s, 5)
    mtest.check_eq(got, np.arange(16, dtype=dt) + dt((r - 1) % s),
                   f"ring {np.dtype(dt).name}")

# distinct tags don't cross-match
if s >= 2 and r < 2:
    peer = 1 - r
    a = comm.isend(np.array([10 + r], np.int32), peer, tag=1)
    b = comm.isend(np.array([20 + r], np.int32), peer, tag=2)
    g2 = np.zeros(1, np.int32)
    g1 = np.zeros(1, np.int32)
    comm.recv(g2, peer, tag=2)
    comm.recv(g1, peer, tag=1)
    a.wait(); b.wait()
    mtest.check_eq(g1[0], 10 + peer, "tag 1 payload")
    mtest.check_eq(g2[0], 20 + peer, "tag 2 payload")

# zero-count message
if s >= 2 and r < 2:
    peer = 1 - r
    comm.sendrecv(np.zeros(0, np.int32), peer, 9,
                  np.zeros(0, np.int32), peer, 9)

mtest.finalize()

"""Persistent requests: send_init/recv_init restarted rounds (ref: pt2pt/
 sendself, persistent patterns)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest

comm = mtest.init()
r, s = comm.rank, comm.size

if s >= 2 and r < 2:
    peer = 1 - r
    sbuf = np.zeros(8, np.float64)
    rbuf = np.zeros(8, np.float64)
    ps = comm.send_init(sbuf, peer, tag=2)
    pr = comm.recv_init(rbuf, peer, tag=2)
    for round_ in range(5):
        sbuf[:] = r * 1000 + round_
        pr.start()
        ps.start()
        ps.wait()
        pr.wait()
        mtest.check_eq(rbuf, np.full(8, peer * 1000 + round_),
                       f"round {round_}")
    ps.free()
    pr.free()

comm.barrier()
mtest.finalize()

"""Message-size spectrum across the eager/rendezvous crossover + ordering
(ref: pt2pt/bsend5-ish size sweeps; protocol split pt2pt/protocol.py)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest

comm = mtest.init()
r, s = comm.rank, comm.size

if s >= 2 and r < 2:
    peer = 1 - r
    sizes = [1, 64, 1024, 8192, 65536, 1 << 18]
    # all sends posted before any recv: ordering must hold per (src,tag)
    reqs = [comm.isend(np.full(n, float(n % 97 + r)), peer, tag=6)
            for n in sizes]
    for n in sizes:
        buf = np.zeros(n)
        comm.recv(buf, peer, tag=6)
        mtest.check_eq(buf[0], float(n % 97 + peer), f"size {n} in order")
        mtest.check_eq(buf[-1], float(n % 97 + peer), f"size {n} tail")
    for q in reqs:
        q.wait()

comm.barrier()
mtest.finalize()

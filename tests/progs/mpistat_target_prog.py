"""mpistat live-attach target: a 2-rank job that prints its shm segment
stem (rank 0, "SEG <path>") and then runs small allreduces for a few
seconds so an external bin/mpistat has live state to attach to. The
duration is MV2T_TEST_STAT_SECONDS (default 6). Prints "No Errors" on
clean completion — the attach must not have perturbed the job.

Launched via: python -m mvapich2_tpu.run -np 2 tests/progs/mpistat_target_prog.py
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, ".")
from mvapich2_tpu import mpi  # noqa: E402

mpi.Init()
comm = mpi.COMM_WORLD
rank = comm.rank

sch = comm.u.shm_channel
if rank == 0:
    print(f"SEG {sch.path if sch is not None else '-'}", flush=True)

# fixed iteration count, NOT a wall-clock deadline: collectives must
# be issued the same number of times on every rank, and a per-rank
# deadline would let one rank reach the barrier while its peer issues
# one more allreduce
iters = int(float(os.environ.get("MV2T_TEST_STAT_SECONDS", "6")) / 0.01)
n = 0
buf = np.ones(16, np.float64)
for _ in range(iters):
    out = comm.allreduce(buf)
    assert out[0] == comm.size
    n += 1
    time.sleep(0.005)

comm.barrier()
if rank == 0:
    print(f"iterations {n}")
    print("No Errors")
mpi.Finalize()

"""Lockcheck-off overhead guard, mirroring trace_overhead_prog.py: with
MV2T_LOCKCHECK unset, ``tracked()`` must return the RAW lock (identity —
zero per-acquisition overhead by construction) and the progress-wait
gate must stay one attribute check. As with the trace guard there is no
un-instrumented build to A/B against, so the guard measures the exact
unit costs on this host and asserts they stay in the noise of the
measured ping-pong latency.

Launched via: python -m mvapich2_tpu.run -np 2 tests/progs/lockcheck_overhead_prog.py
"""

import sys
import threading
import time

import numpy as np

sys.path.insert(0, ".")
from mvapich2_tpu import mpi  # noqa: E402
from mvapich2_tpu.analysis import lockorder  # noqa: E402

ITERS = 300
SKIP = 50
GATE_SITES = 4      # _lockcheck-is-None checks per message (wait cycles)

mpi.Init()
comm = mpi.COMM_WORLD
rank, size = comm.rank, comm.size
assert size == 2, "lockcheck_overhead_prog requires exactly 2 ranks"

sbuf = np.zeros(8, np.uint8)
rbuf = np.zeros(8, np.uint8)
comm.barrier()
if rank == 0:
    for i in range(ITERS + SKIP):
        if i == SKIP:
            t0 = time.perf_counter()
        comm.send(sbuf, dest=1, tag=1)
        comm.recv(rbuf, source=1, tag=1)
    lat = (time.perf_counter() - t0) / ITERS / 2    # one-way seconds
else:
    for i in range(ITERS + SKIP):
        comm.recv(rbuf, source=0, tag=1)
        comm.send(sbuf, dest=0, tag=1)

errs = 0
if rank == 0 and lockorder.get_monitor() is not None:
    print("MV2T_LOCKCHECK is ON; skipping the off-overhead guard")
elif rank == 0:
    eng = comm.u.engine
    # off => tracked() is the identity: the engine's own mutex must be a
    # plain RLock, not a TrackedLock proxy
    raw = threading.Lock()
    if lockorder.tracked(raw, "probe") is not raw:
        print("tracked() wrapped a lock with MV2T_LOCKCHECK off")
        errs += 1
    if type(eng.mutex).__name__ == "TrackedLock":
        print("engine mutex is wrapped with MV2T_LOCKCHECK off")
        errs += 1
    if eng._lockcheck is not None:
        print("engine._lockcheck armed with MV2T_LOCKCHECK off")
        errs += 1

    n = 200000
    t0 = time.perf_counter()
    hits = 0
    for _ in range(n):
        if eng._lockcheck is not None:      # the exact off-gate
            hits += 1
    t_gate = (time.perf_counter() - t0) / n
    assert hits == 0

    overhead = GATE_SITES * t_gate
    frac = overhead / lat
    print(f"latency {lat * 1e6:.2f} us/msg; gate {t_gate * 1e9:.1f} ns; "
          f"lockcheck-off overhead {overhead * 1e6:.4f} us/msg = "
          f"{frac * 100:.3f}% of latency")
    if frac >= 0.05:
        errs += 1
        print(f"lockcheck-off overhead {frac * 100:.2f}% >= 5% budget")

mpi.Finalize()
if errs == 0 and rank == 0:
    print(" No Errors")
sys.exit(1 if errs else 0)

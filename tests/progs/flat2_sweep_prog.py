"""Rank program: python-API correctness sweep of the HIERARCHICAL
flat tier + multicast bcast (coll/flatcoll.py -> cp_flat2_*), the
np > 8 sibling of flatpy_sweep_prog.py. Run at np in {9..64}.

Covers: allreduce/reduce/bcast/barrier across ops x dtypes x sizes
straddling the flat2 payload max (4 KiB) and the group boundaries
(counts chosen so k does and does not divide np at the default k=8 and
under MV2T_FLAT2_GROUP overrides), long pipelined bcast streams from
rotating roots (the mcast ring's depth > MCAST_NBUF), dup'd and split
comms (split halves of np >= 18 land back in the flat2 window; smaller
halves exercise the flat<->flat2 dispatch split), and context reuse.
Asserts the flat2 tier actually carried work (fp_coll_flat2 moved) so
the sweep cannot silently pass on a fallback.

Launched via: python -m mvapich2_tpu.run -np N tests/progs/flat2_sweep_prog.py
"""

import sys

import numpy as np

sys.path.insert(0, ".")
from mvapich2_tpu import mpi                        # noqa: E402

mpi.Init()
comm = mpi.COMM_WORLD
rank, size = comm.rank, comm.size
errs = 0

# int32 element counts straddling the 4 KiB flat2 max (1024 elements)
COUNTS = (1, 7, 64, 1023, 1024, 1025, 4096)
OPS = ((mpi.SUM, "sum"), (mpi.MAX, "max"), (mpi.MIN, "min"))


def sweep(c):
    global errs
    n, r_ = c.size, c.rank
    for cnt in COUNTS:
        s = (np.arange(cnt) % 97 + r_ + 1).astype(np.int32)
        out = np.zeros(cnt, np.int32)
        c.allreduce(s, out)
        want = (np.arange(cnt) % 97 + 1).astype(np.int64) * n \
            + n * (n - 1) // 2
        if not np.array_equal(out.astype(np.int64), want):
            errs += 1
            print(f"rank {r_}: allreduce sum cnt={cnt} wrong")
    for dt in (np.int32, np.float64, np.int64, np.uint8):
        for op, _name in OPS:
            s = (np.arange(17) % 5 + r_ + 1).astype(dt)
            out = np.zeros(17, dt)
            c.allreduce(s, out, op)
            ref = np.stack([(np.arange(17) % 5 + rr + 1).astype(dt)
                            for rr in range(n)])
            want = {mpi.SUM: ref.sum(0, dtype=dt),
                    mpi.MAX: ref.max(0), mpi.MIN: ref.min(0)}[op]
            if not np.array_equal(out, want):
                errs += 1
                print(f"rank {r_}: allreduce {_name} {dt.__name__} wrong")
    # reduce to group-boundary roots (group leaders AND mid-group
    # members at the default k=8), bcast from rotating roots, barriers
    roots = sorted({0, 1, n - 1, min(8, n - 1), min(9, n - 1)})
    for root in roots:
        s = np.full(9, r_ + 2, np.int64)
        out = np.zeros(9, np.int64)
        c.reduce(s, out, mpi.SUM, root)
        if r_ == root and not np.all(out == sum(x + 2 for x in range(n))):
            errs += 1
            print(f"rank {r_}: reduce root={root} wrong")
        b = np.full(33, root + 7, np.int32) if r_ == root \
            else np.zeros(33, np.int32)
        c.bcast(b, root)
        if not np.all(b == root + 7):
            errs += 1
            print(f"rank {r_}: bcast root={root} wrong")
        c.barrier()
    # pipelined mcast stream: one root, > MCAST_NBUF consecutive waves
    # with per-wave payloads (a stale or torn ring buffer shows up as a
    # wrong wave's value), lengths crossing the buffer header path
    for i in range(20):
        nb = (i % 3 + 1) * 128
        b = np.full(nb, i * 11 + 3, np.int32) if r_ == 0 \
            else np.zeros(nb, np.int32)
        c.bcast(b, 0)
        if not np.all(b == i * 11 + 3):
            errs += 1
            print(f"rank {r_}: mcast stream wave {i} wrong")


sweep(comm)

dup = comm.dup()
sweep(dup)
dup.free()

if size >= 2:
    half = comm.split(rank % 2, rank)
    sweep(half)
    half.free()
    # context reuse: the freed id returns; renumbering must be clean
    half2 = comm.split(rank % 2, rank)
    sweep(half2)
    half2.free()

# the flat2 tier must actually have carried the small ops
pch = getattr(comm.u, "plane_channel", None)
if pch is not None and pch.plane \
        and pch._ring.lib.cp_flat2_ok(pch.plane):
    flat2 = pch.fp_counter(12)    # FPC_COLL_FLAT2
    if flat2 < 20:
        errs += 1
        print(f"rank {rank}: flat2 tier not exercised "
              f"(fp_coll_flat2={flat2})")

total = np.zeros(1, np.int32)
comm.allreduce(np.full(1, errs, np.int32), total)
if rank == 0:
    print("No Errors" if total[0] == 0 else f"{total[0]} errors")
mpi.Finalize()
sys.exit(1 if total[0] else 0)

/* f77_abi_test.c — drives the Fortran binding layer (mpif.c) from C
 * through the exact f77 calling convention (by-reference args, status
 * arrays, hidden string lengths, MPIPRIV common for MPI_IN_PLACE), so
 * the binding is validated even on hosts without a Fortran compiler.
 * Prints "No Errors" (runtests contract). */
#include <math.h>
#include <stdio.h>
#include <string.h>

/* f77-ABI prototypes (as gfortran would emit calls) */
void mpi_init_(int *ierr);
void mpi_finalize_(int *ierr);
void mpi_comm_rank_(int *comm, int *rank, int *ierr);
void mpi_comm_size_(int *comm, int *size, int *ierr);
void mpi_sendrecv_(void *sb, int *sc, int *sdt, int *dest, int *stag,
                   void *rb, int *rc, int *rdt, int *src, int *rtag,
                   int *comm, int *status, int *ierr);
void mpi_allreduce_(void *sb, void *rb, int *count, int *dt, int *op,
                    int *comm, int *ierr);
void mpi_bcast_(void *buf, int *count, int *dt, int *root, int *comm,
                int *ierr);
void mpi_isend_(void *buf, int *count, int *dt, int *dest, int *tag,
                int *comm, int *req, int *ierr);
void mpi_irecv_(void *buf, int *count, int *dt, int *src, int *tag,
                int *comm, int *req, int *ierr);
void mpi_waitall_(int *count, int *reqs, int *statuses, int *ierr);
void mpi_get_count_(int *status, int *dt, int *count, int *ierr);
void mpi_get_processor_name_(char *name, int *len, int *ierr,
                             long name_len);
void mpi_scan_(void *sb, void *rb, int *count, int *dt, int *op,
               int *comm, int *ierr);
void mpi_type_vector_(int *count, int *bl, int *stride, int *oldtype,
                      int *newtype, int *ierr);
void mpi_type_commit_(int *dt, int *ierr);
void mpi_type_free_(int *dt, int *ierr);
double mpi_wtime_(void);
extern struct { int bottom; int in_place; } mpipriv_;

#define F_COMM_WORLD 0
#define F_INTEGER 2
#define F_DOUBLE 4
#define F_SUM 0

static int errs = 0;
static int rank, size;

#define CHECK(c, m) do { \
    if (!(c)) { errs++; fprintf(stderr, "rank %d: %s\n", rank, m); } \
} while (0)

int main(void) {
    int ierr, comm = F_COMM_WORLD;
    mpi_init_(&ierr);
    mpi_comm_rank_(&comm, &rank, &ierr);
    mpi_comm_size_(&comm, &size, &ierr);

    /* ring sendrecv with a Fortran status array */
    int idt = F_INTEGER, tag = 5;
    int right = (rank + 1) % size, left = (rank + size - 1) % size;
    int sbuf[8], rbuf[8], status[4], n = 8;
    for (int i = 0; i < 8; i++) { sbuf[i] = rank * 100 + i; rbuf[i] = -1; }
    mpi_sendrecv_(sbuf, &n, &idt, &right, &tag, rbuf, &n, &idt, &left,
                  &tag, &comm, status, &ierr);
    CHECK(ierr == 0, "sendrecv ierr");
    for (int i = 0; i < 8; i++)
        CHECK(rbuf[i] == left * 100 + i, "ring payload");
    CHECK(status[0] == left && status[1] == 5, "status fields");
    int got = 0;
    mpi_get_count_(status, &idt, &got, &ierr);
    CHECK(got == 8, "get_count");

    /* allreduce doubles + MPI_IN_PLACE via the MPIPRIV common */
    int ddt = F_DOUBLE, op = F_SUM, c4 = 4;
    double v[4], w[4];
    for (int i = 0; i < 4; i++) v[i] = rank + i + 1.0;
    mpi_allreduce_(v, w, &c4, &ddt, &op, &comm, &ierr);
    for (int i = 0; i < 4; i++)
        CHECK(fabs(w[i] - (size * (i + 1.0) + size * (size - 1) / 2.0))
              < 1e-9, "allreduce");
    double ip[2] = {1.0 + rank, 2.0};
    int c2 = 2;
    mpi_allreduce_(&mpipriv_.in_place, ip, &c2, &ddt, &op, &comm, &ierr);
    CHECK(fabs(ip[0] - (size + size * (size - 1) / 2.0)) < 1e-9,
          "allreduce IN_PLACE");

    /* isend/irecv + waitall */
    int reqs[2], sts[8], two = 2, one = 1;
    int sv = rank * 7, rv = -1;
    mpi_irecv_(&rv, &one, &idt, &left, &tag, &comm, &reqs[0], &ierr);
    mpi_isend_(&sv, &one, &idt, &right, &tag, &comm, &reqs[1], &ierr);
    mpi_waitall_(&two, reqs, sts, &ierr);
    CHECK(rv == left * 7, "isend/irecv");

    /* scan */
    int si = rank + 1, so = 0;
    mpi_scan_(&si, &so, &one, &idt, &op, &comm, &ierr);
    CHECK(so == (rank + 1) * (rank + 2) / 2, "scan");

    /* hidden-length CHARACTER arg */
    char name[64];
    int nl = 0;
    memset(name, 0, sizeof(name));
    mpi_get_processor_name_(name, &nl, &ierr, (long)sizeof(name));
    CHECK(nl > 0 && name[0] != ' ', "processor name");

    /* derived type handle through the f77 layer */
    int vec = -1, cnt2 = 2, bl = 1, stride = 2;
    mpi_type_vector_(&cnt2, &bl, &stride, &idt, &vec, &ierr);
    CHECK(vec >= 100, "type_vector handle");
    mpi_type_commit_(&vec, &ierr);
    mpi_type_free_(&vec, &ierr);

    CHECK(mpi_wtime_() > 0.0, "wtime");

    int tot = 0;
    mpi_allreduce_(&errs, &tot, &one, &idt, &op, &comm, &ierr);
    if (rank == 0 && tot == 0)
        printf("No Errors\n");
    mpi_finalize_(&ierr);
    return tot ? 1 : 0;
}

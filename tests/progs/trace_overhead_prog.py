"""Trace-overhead guard: tracing-off must stay in the noise on the
osu_latency-shaped ping-pong path, so the recorder can stay compiled-in.

The trace-off cost at every instrumented site is ONE attribute check
(``engine.tracer is None``) plus, on the channel layer, the per-packet
pvar increments. There is no un-instrumented build to A/B against, so
the guard measures those exact unit costs on this host, scales them by a
deliberately generous per-message site count, and asserts the total is
under 5% of the measured per-message latency. If someone fattens the
gate (a config lookup, a dict build) or slows PVar.inc, this trips.

Launched via: python -m mvapich2_tpu.run -np 2 tests/progs/trace_overhead_prog.py
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")
from mvapich2_tpu import mpi, mpit  # noqa: E402

ITERS = 300
SKIP = 50
# per ping-pong message, generous upper bounds for trace-off work:
GATE_SITES = 16     # tracer-is-None checks (mpi/protocol/progress/nbc/chan)
PVINC_SITES = 8     # channel + protocol counter increments
# native ring off (ISSUE 10): every MV2T_NTRACE site in cplane.cpp is
# ONE pointer-NULL branch (p->nt_mine) — strictly cheaper than the
# python attribute check measured below, so modeling the C sites with
# the python gate's unit cost OVERSTATES them. Generous per-message
# count: eager tx+rx, bell ring, spin->bell, wake, flat fan-in/fold/
# fan-out, dispatch, plus slack.
NTRACE_SITES = 12
# metrics-off (ISSUE 17): every histogram site is ONE module-attribute
# check (``metrics.LIVE is None``) — same discipline, measured with
# its own unit cost below. Generous per-message count: collective
# flat/sched gates, rendezvous drain/publish, RMA, plus slack.
METRICS_SITES = 8

mpi.Init()
comm = mpi.COMM_WORLD
rank, size = comm.rank, comm.size
assert size == 2, "trace_overhead_prog requires exactly 2 ranks"

sbuf = np.zeros(8, np.uint8)
rbuf = np.zeros(8, np.uint8)
comm.barrier()
if rank == 0:
    for i in range(ITERS + SKIP):
        if i == SKIP:
            t0 = time.perf_counter()
        comm.send(sbuf, dest=1, tag=1)
        comm.recv(rbuf, source=1, tag=1)
    lat = (time.perf_counter() - t0) / ITERS / 2    # one-way seconds
else:
    for i in range(ITERS + SKIP):
        comm.recv(rbuf, source=0, tag=1)
        comm.send(sbuf, dest=0, tag=1)

errs = 0
if rank == 0 and comm.u.engine.tracer is not None:
    # run under bin/mpitrace: the off-cost guard is meaningless with the
    # recorder attached — report and pass (the tier-1 test runs untraced)
    print("tracing is ON; skipping the trace-off overhead guard")
elif rank == 0:
    # the native ring must actually be OFF for this budget to be the
    # trace-off cost (MV2T_NTRACE unset follows MV2T_TRACE, also off)
    sch = comm.u.shm_channel
    if sch is not None and getattr(sch, "ntrace_active", lambda: False)():
        print("native trace ring is ON; overhead guard expects it off")
        errs += 1
    eng = comm.u.engine
    n = 200000
    t0 = time.perf_counter()
    hits = 0
    for _ in range(n):
        if eng.tracer is not None:      # the exact trace-off gate
            hits += 1
    t_gate = (time.perf_counter() - t0) / n
    assert hits == 0

    pv = mpit.pvar("trace_overhead_probe", mpit.PVAR_CLASS_COUNTER,
                   "test", "overhead-guard probe counter")
    t0 = time.perf_counter()
    for _ in range(n):
        pv.inc()
    t_inc = (time.perf_counter() - t0) / n

    # the metrics-off branch: the exact gate the histogram sites pay
    # when MV2T_METRICS=0 (module attribute read + None check). The
    # job here runs with metrics ON (the default), so LIVE is not None
    # and the measured cost is the on-path check — an upper bound on
    # the off-path one (same lookup, same branch shape).
    from mvapich2_tpu import metrics as _metrics
    t0 = time.perf_counter()
    seen = 0
    for _ in range(n):
        if _metrics.LIVE is not None:   # the exact metrics gate
            seen += 1
    t_met = (time.perf_counter() - t0) / n

    overhead = (GATE_SITES + NTRACE_SITES) * t_gate \
        + PVINC_SITES * t_inc + METRICS_SITES * t_met
    frac = overhead / lat
    print(f"latency {lat * 1e6:.2f} us/msg; gate {t_gate * 1e9:.1f} ns; "
          f"pvar.inc {t_inc * 1e9:.1f} ns; metrics gate "
          f"{t_met * 1e9:.1f} ns; trace-off overhead "
          f"(incl. {NTRACE_SITES} native ring-off branches and "
          f"{METRICS_SITES} metrics gates) "
          f"{overhead * 1e6:.3f} us/msg = {frac * 100:.2f}% of latency")
    if frac >= 0.05:
        errs += 1
        print(f"trace-off overhead {frac * 100:.2f}% >= 5% budget")

    # sampler-on smoke budget: one tick (fp-mirror slice + a dozen
    # pvar reads + ~600 B of struct packing) must cost well under one
    # sampling interval — the heartbeat thread absorbs it without ever
    # falling behind the lease cadence. Budget: 1% of the 250 ms
    # default interval (2.5 ms/tick) — generous by ~3 orders on any
    # plausible host, but catches an accidental O(ring) or O(n_local)
    # regression in the tick path.
    smp = getattr(sch, "_sampler", None) if sch is not None else None
    if smp is not None and not smp.dead:
        reps = 200
        t0 = time.perf_counter()
        for _ in range(reps):
            smp.tick()
        t_tick = (time.perf_counter() - t0) / reps
        print(f"sampler tick {t_tick * 1e6:.2f} us "
              f"(budget {0.01 * smp.interval * 1e6:.0f} us)")
        if t_tick >= 0.01 * smp.interval:
            errs += 1
            print(f"sampler tick {t_tick * 1e6:.1f} us exceeds 1% of "
                  f"the {smp.interval * 1e3:.0f} ms interval")

comm.barrier()
if rank == 0 and errs == 0:
    print("No Errors")
mpi.Finalize()
sys.exit(1 if errs else 0)

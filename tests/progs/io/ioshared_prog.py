"""Shared/ordered file pointers (ref: io/shared_fp, ordered_fp)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import tempfile
import numpy as np
import mtest
from mvapich2_tpu import mpi
from mvapich2_tpu.io import adio

comm = mtest.init()
r, s = comm.rank, comm.size

job = os.environ.get("MV2T_KVS", "local").replace("/", "_").replace(
    ":", "_")
path = os.path.join(tempfile.gettempdir(), f"mv2t_ioshared_{job}.bin")

fh = mpi.File_open(comm, path, adio.MODE_RDWR | adio.MODE_CREATE)

# ordered write: rank order deterministic
fh.write_ordered(np.full(4, float(r), np.float64))
comm.barrier()
if r == 0:
    raw = np.fromfile(path, np.float64)
    want = np.concatenate([np.full(4, float(i)) for i in range(s)])
    mtest.check_eq(raw, want, "write_ordered layout")

# shared-pointer writes land in disjoint regions (order unspecified)
fh.seek_shared(s * 4 * 8)
comm.barrier()
fh.write_shared(np.full(2, float(100 + r), np.float64))
comm.barrier()
if r == 0:
    raw = np.fromfile(path, np.float64)[s * 4:]
    got = sorted(raw.tolist())
    want = sorted(sum([[100.0 + i] * 2 for i in range(s)], []))
    mtest.check_eq(got, want, "write_shared disjoint")
fh.close()
comm.barrier()
if r == 0:
    os.unlink(path)

mtest.finalize()

"""MPI-IO: write_at/read_at, views, collective write_all (ref: io/rdwrord,
setviewcur)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import tempfile
import numpy as np
import mtest
from mvapich2_tpu import mpi
from mvapich2_tpu.core import datatype as dt
from mvapich2_tpu.io import adio

comm = mtest.init()
r, s = comm.rank, comm.size

job = os.environ.get("MV2T_KVS", "local").replace("/", "_").replace(
    ":", "_")
path = os.path.join(tempfile.gettempdir(), f"mv2t_iorw_{job}.bin")
amode = adio.MODE_RDWR | adio.MODE_CREATE

fh = mpi.File_open(comm, path, amode)
# each rank writes its block at offset r*64
data = (np.arange(8, dtype=np.float64) + 10 * r)
fh.write_at(r * 64, data)
fh.close()
comm.barrier()

fh = mpi.File_open(comm, path, adio.MODE_RDONLY)
back = np.zeros(8)
fh.read_at(((r + 1) % s) * 64, back)
mtest.check_eq(back, np.arange(8, dtype=np.float64) + 10 * ((r + 1) % s),
               "read_at neighbor block")

# file view: rank r sees every s-th double (stride pattern)
vec = dt.create_vector(8, 1, s, dt.DOUBLE).commit()
fh.set_view(r * 8, etype=dt.DOUBLE, filetype=vec)
strided = np.zeros(8)
fh.read(strided)
whole = np.concatenate([np.arange(8, dtype=np.float64) + 10 * i
                        for i in range(s)])
mtest.check_eq(strided, whole[r::s], "strided view read")
fh.close()

# collective write_at_all through per-rank views
comm.barrier()
fh = mpi.File_open(comm, path, amode)
fh.set_view(r * 16, etype=dt.DOUBLE, filetype=dt.DOUBLE)
fh.write_at_all(0, np.full(2, float(r)))
fh.close()
comm.barrier()
if r == 0:
    raw = np.fromfile(path, np.float64)
    for i in range(s):
        mtest.check_eq(raw[2 * i: 2 * i + 2], np.full(2, float(i)),
                       f"write_at_all block {i}")
    os.unlink(path)

mtest.finalize()

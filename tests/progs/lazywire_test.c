/* lazywire_test.c — lazy-wiring first contact through the C ABI.
 * Mode argv[1] (default "eager"):
 *   eager  4 B ring sendrecv before any collective (must complete
 *          while the node is unwired), then an allreduce
 *   rndv   512 KiB pairwise exchange first (rendezvous ladder
 *          degrades to scratch-file pre-wire, upgrades in place)
 *   flat   small allreduce loop first (the shim's collective gate
 *          wires the node, later iterations ride the C flat tier)
 *   arena  1 MiB allreduce first (arena/CMA sectioned tier)
 * Prints "No Errors" from rank 0 (tests/test_lazy_wiring.py). */
#include <mpi.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

static int errs = 0;

int main(int argc, char **argv) {
    const char *mode = argc > 1 ? argv[1] : "eager";
    MPI_Init(&argc, &argv);
    int rank, size;
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &size);
    int peer = rank ^ 1;

    if (!strcmp(mode, "eager") && peer < size) {
        int s = rank + 1, r = -1;
        MPI_Sendrecv(&s, 1, MPI_INT, peer, 7, &r, 1, MPI_INT, peer, 7,
                     MPI_COMM_WORLD, MPI_STATUS_IGNORE);
        if (r != peer + 1) {
            errs++;
            fprintf(stderr, "rank %d: eager got %d want %d\n",
                    rank, r, peer + 1);
        }
    } else if (!strcmp(mode, "rndv") && peer < size) {
        long n = 512 * 1024;
        unsigned char *s = malloc(n), *r = malloc(n);
        for (long i = 0; i < n; i++) s[i] = (unsigned char)(i + rank);
        memset(r, 0, n);
        MPI_Sendrecv(s, (int)n, MPI_BYTE, peer, 9, r, (int)n, MPI_BYTE,
                     peer, 9, MPI_COMM_WORLD, MPI_STATUS_IGNORE);
        for (long i = 0; i < n; i++)
            if (r[i] != (unsigned char)(i + peer)) {
                errs++;
                fprintf(stderr, "rank %d: rndv mismatch at %ld\n",
                        rank, i);
                break;
            }
        free(s);
        free(r);
    } else if (!strcmp(mode, "arena")) {
        long n = (1 << 20) / sizeof(double);
        double *s = malloc(n * sizeof(double));
        double *r = malloc(n * sizeof(double));
        for (long i = 0; i < n; i++) s[i] = rank + 1.0;
        MPI_Allreduce(s, r, (int)n, MPI_DOUBLE, MPI_SUM,
                      MPI_COMM_WORLD);
        double want = size * (size + 1) / 2.0;
        if (r[0] != want || r[n - 1] != want) {
            errs++;
            fprintf(stderr, "rank %d: arena allreduce got %f want %f\n",
                    rank, r[0], want);
        }
        free(s);
        free(r);
    }

    /* every mode finishes with small allreduces: wires the node if the
     * first contact didn't, and exercises the post-wire flat tier */
    for (int it = 0; it < 5; it++) {
        int x = rank + it, y = -1;
        MPI_Allreduce(&x, &y, 1, MPI_INT, MPI_SUM, MPI_COMM_WORLD);
        int want = it * size + size * (size - 1) / 2;
        if (y != want) {
            errs++;
            fprintf(stderr, "rank %d: flat allreduce it=%d got %d "
                            "want %d\n", rank, it, y, want);
        }
    }

    int tot = 0;
    MPI_Allreduce(&errs, &tot, 1, MPI_INT, MPI_SUM, MPI_COMM_WORLD);
    if (rank == 0 && tot == 0)
        printf("No Errors\n");
    MPI_Finalize();
    return tot ? 1 : 0;
}

"""Spawned child: joins via KVS, talks to parents over the spawn
intercomm (launched by spawn_parent_prog.py)."""

import sys

import numpy as np

sys.path.insert(0, ".")
from mvapich2_tpu import mpi  # noqa: E402

mpi.Init()
cw = mpi.COMM_WORLD
parent = mpi.Comm_get_parent()
assert parent is not None and parent.is_inter, "no parent intercomm"
assert cw.size == 2, f"child world size {cw.size}"

out = parent.allreduce(np.array([100 + cw.rank], dtype=np.int64))
# parents contributed rank+1 each over a 2-rank world: 1 + 2
assert int(out[0]) == 3, f"child saw parent sum {out[0]}"

merged = parent.merge(high=True)
assert merged.size == parent.remote_size + cw.size
tot = merged.allreduce(np.ones(1))
assert int(tot[0]) == merged.size

parent.barrier()
mpi.Finalize()
sys.exit(0)

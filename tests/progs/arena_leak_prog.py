"""Rank program: arena handle leak detection at Finalize.

Rank 0 exposes a send buffer for remote pull (the rendezvous RGET
registration) and never releases it — simulating a lost FIN. The
channel's close() leak check must notice the live exposure and warn.
The warning goes to stderr via mlog; we hook the shm logger to mirror
a LEAK-DETECTED marker onto stdout for the harness to assert on.

Launched via: python -m mvapich2_tpu.run -np 2 tests/progs/arena_leak_prog.py
(with MV2T_USE_CMA=0 so the exposure takes an arena block)
"""

import sys

import numpy as np

sys.path.insert(0, ".")
from mvapich2_tpu import mpi                        # noqa: E402
from mvapich2_tpu.transport import shm as shm_mod   # noqa: E402

class _HookLog:
    """Proxy around the slotted mlog Logger that mirrors leak warnings."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def warn(self, msg, *args):
        if "leak" in msg:
            print("LEAK-DETECTED: " + (msg % args if args else msg),
                  flush=True)
        self._inner.warn(msg, *args)


shm_mod.log = _HookLog(shm_mod.log)

mpi.Init()
comm = mpi.COMM_WORLD
rank = comm.rank

ch = comm.u.shm_channel
if ch is None:
    if rank == 0:
        print("LEAK-DETECTED: (no shm channel; vacuous)", flush=True)
    mpi.Finalize()
    sys.exit(0)

if rank == 0:
    h = ch.expose_buffer(np.ones(256 * 1024, dtype=np.uint8))
    kind = h[0] if isinstance(h, tuple) else "path"
    # file handles carry no table entry; the leak check covers the
    # registered kinds (cma / arena)
    if kind == "file":
        ch.release_buffer(h)
        print("LEAK-DETECTED: (arena unavailable; file path has no "
              "handle table — vacuous)", flush=True)

comm.barrier()
mpi.Finalize()
sys.exit(0)

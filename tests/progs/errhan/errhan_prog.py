"""Error classes + exceptions on invalid usage (ref: errhan/errstring,
adderr)."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import mtest
from mvapich2_tpu.core import errors as err

comm = mtest.init()
r, s = comm.rank, comm.size

try:
    comm.send(np.zeros(1), dest=s + 5)
    mtest.error("send to invalid rank did not raise")
except err.MPIException as e:
    mtest.check_eq(e.error_class, err.MPI_ERR_RANK, "invalid rank class")

try:
    comm.split(0, 0).free() if False else None
    bad = comm.bcast(np.zeros(1), root=-3)
    mtest.error("bcast invalid root did not raise")
except err.MPIException as e:
    mtest.check(e.error_class in (err.MPI_ERR_ROOT, err.MPI_ERR_RANK),
                "invalid root class")

# error strings exist for every class
for cls in (err.MPI_ERR_RANK, err.MPI_ERR_TAG, err.MPI_ERR_COMM,
            err.MPI_ERR_TRUNCATE, err.MPI_ERR_OTHER):
    msg = err.error_string(cls)
    mtest.check(isinstance(msg, str) and msg, f"error_string({cls})")

# truncation: recv buffer smaller than message
if s >= 2 and r < 2:
    peer = 1 - r
    if r == 0:
        comm.send(np.zeros(8), 1, tag=1)
        comm.recv(np.zeros(1), 1, tag=2)
    else:
        try:
            comm.recv(np.zeros(2), 0, tag=1)
            mtest.error("truncation did not raise")
        except err.MPIException as e:
            mtest.check_eq(e.error_class, err.MPI_ERR_TRUNCATE, "truncate class")
        comm.send(np.zeros(1), 0, tag=2)

comm.barrier()
mtest.finalize()

"""Rank program: python-API correctness sweep of the flat-slot
collective tier (coll/flatcoll.py -> cp_flat_*), mirroring the C-ABI
sweep in flatcoll_test.c: allreduce/reduce/bcast/barrier across ops x
dtypes x sizes straddling the protocol boundaries (flat payload max,
eager size, FP_COLL_MAX), plus dup'd and split comms so the
per-(context, lane) regions and numbering bases see comm churn. Also
verifies the flat tier actually carried small collectives
(fp_coll_flat moved) so the sweep cannot silently pass on a fallback.

Launched via: python -m mvapich2_tpu.run -np N tests/progs/flatpy_sweep_prog.py
"""

import sys

import numpy as np

sys.path.insert(0, ".")
from mvapich2_tpu import mpi                        # noqa: E402

mpi.Init()
comm = mpi.COMM_WORLD
rank, size = comm.rank, comm.size
errs = 0

# element counts chosen so int32 payloads straddle the 4 KiB flat max,
# the 32 KiB eager size, and fall inside the scheduled band
COUNTS = (1, 64, 1024, 1025, 2048, 8192, 8193, 65536)
OPS = ((mpi.SUM, "sum"), (mpi.MAX, "max"), (mpi.MIN, "min"),
       (mpi.PROD, "prod"))


def sweep(c):
    global errs
    n, r_ = c.size, c.rank
    for cnt in COUNTS:
        s = (np.arange(cnt) % 97 + r_ + 1).astype(np.int32)
        out = np.zeros(cnt, np.int32)
        c.allreduce(s, out)
        want = (np.arange(cnt) % 97 + 1).astype(np.int64) * n \
            + n * (n - 1) // 2
        if not np.array_equal(out.astype(np.int64), want):
            errs += 1
            print(f"rank {r_}: allreduce sum cnt={cnt} wrong")
    # dtype x op coverage at flat-tier sizes
    for dt in (np.int32, np.float64, np.int64, np.uint8, np.int16,
               np.float32):
        for op, _name in OPS:
            if dt == np.uint8 and op is mpi.PROD:
                continue        # overflow-wraps; not a useful check
            s = (np.arange(17) % 5 + r_ + 1).astype(dt)
            out = np.zeros(17, dt)
            c.allreduce(s, out, op)
            ref = np.stack([(np.arange(17) % 5 + rr + 1).astype(dt)
                            for rr in range(n)])
            want = {mpi.SUM: ref.sum(0, dtype=dt),
                    mpi.MAX: ref.max(0), mpi.MIN: ref.min(0),
                    mpi.PROD: ref.prod(0, dtype=dt)}[op]
            if not np.array_equal(out, want):
                errs += 1
                print(f"rank {r_}: allreduce {_name} {dt.__name__} wrong")
    # reduce to every root; bcast from every root; barriers interleaved
    for root in range(n):
        s = np.full(9, r_ + 2, np.int64)
        out = np.zeros(9, np.int64)
        c.reduce(s, out, mpi.SUM, root)
        if r_ == root and not np.all(out == sum(x + 2 for x in range(n))):
            errs += 1
            print(f"rank {r_}: reduce root={root} wrong")
        b = np.full(33, root + 7, np.int32) if r_ == root \
            else np.zeros(33, np.int32)
        c.bcast(b, root)
        if not np.all(b == root + 7):
            errs += 1
            print(f"rank {r_}: bcast root={root} wrong")
        c.barrier()


sweep(comm)

dup = comm.dup()
sweep(dup)
dup.free()

if size >= 2:
    half = comm.split(rank % 2, rank)
    sweep(half)
    half.free()
    # context reuse: the freed id returns; renumbering must be clean
    half2 = comm.split(rank % 2, rank)
    sweep(half2)
    half2.free()

# the flat tier must actually have carried the small ops
pch = getattr(comm.u, "plane_channel", None)
if pch is not None and pch.plane and pch._ring.lib.cp_flat_ok(pch.plane):
    flat = pch.fp_counter(6)    # FPC_COLL_FLAT
    if flat < 10:
        errs += 1
        print(f"rank {rank}: flat tier not exercised (fp_coll_flat={flat})")

total = np.zeros(1, np.int32)
comm.allreduce(np.full(1, errs, np.int32), total)
if rank == 0:
    print("No Errors" if total[0] == 0 else f"{total[0]} errors")
mpi.Finalize()
sys.exit(1 if total[0] else 0)

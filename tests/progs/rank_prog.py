"""Process-mode smoke program: the 'prints No Errors' contract (SURVEY §4).

Launched by tests via: python -m mvapich2_tpu.run -np N tests/progs/rank_prog.py
"""

import sys

import numpy as np

sys.path.insert(0, ".")
from mvapich2_tpu import mpi  # noqa: E402

mpi.Init()
comm = mpi.COMM_WORLD
rank, size = comm.rank, comm.size

errs = 0

# pt2pt ring shift, eager
mine = np.array([rank], np.int64)
got = np.zeros(1, np.int64)
comm.sendrecv(mine, (rank + 1) % size, 0, got, (rank - 1) % size, 0)
if got[0] != (rank - 1) % size:
    errs += 1
    print(f"rank {rank}: ring shift wrong: {got[0]}")

# rendezvous-sized pt2pt
big = np.full(1 << 17, float(rank), np.float64)
rbig = np.zeros(1 << 17, np.float64)
comm.sendrecv(big, (rank + 1) % size, 1, rbig, (rank - 1) % size, 1)
if rbig[0] != float((rank - 1) % size):
    errs += 1
    print(f"rank {rank}: big sendrecv wrong")

# collectives
out = comm.allreduce(np.full(1000, float(rank + 1)))
if abs(out[0] - sum(range(1, size + 1))) > 1e-9:
    errs += 1
    print(f"rank {rank}: allreduce wrong: {out[0]}")

buf = np.arange(64, dtype=np.int32) if rank == 0 else np.zeros(64, np.int32)
comm.bcast(buf, root=0)
if buf[10] != 10:
    errs += 1
    print(f"rank {rank}: bcast wrong")

gat = comm.allgather(np.array([rank * 7], np.int32))
if gat.tolist() != [r * 7 for r in range(size)]:
    errs += 1
    print(f"rank {rank}: allgather wrong: {gat}")

comm.barrier()
if rank == 0 and errs == 0:
    print("No Errors")
mpi.Finalize()
sys.exit(1 if errs else 0)

"""Rank program: the C plane's counters are observable via an MPI_T
pvar session while the job runs (mv2_mpit.c:17-39 channel-counter
discipline — the fast-path hit-rate for this very workload).

Launched via: python -m mvapich2_tpu.run -np 2 tests/progs/pvar_plane_prog.py
"""

import sys

import numpy as np

sys.path.insert(0, ".")
from mvapich2_tpu import mpi, mpit  # noqa: E402

mpi.Init()
comm = mpi.COMM_WORLD
rank, size = comm.rank, comm.size

sess = mpit.pvar_session_create()
handles = {n: sess.handle_alloc(n)
           for n in ("cplane_eager_tx", "cplane_eager_rx", "cplane_fwd_py")}
for h in handles.values():
    sess.start(h)

buf = np.full(8, rank, dtype=np.float64)
out = np.zeros(8, dtype=np.float64)
comm.sendrecv(buf, (rank + 1) % size, 9, out, (rank - 1) % size, 9)

errs = 0
u = comm.u
pch = getattr(u, "plane_channel", None)
if pch is not None and pch.plane:
    tx = sess.read(handles["cplane_eager_tx"])
    rx = sess.read(handles["cplane_eager_rx"])
    if tx < 1:
        errs += 1
        print(f"rank {rank}: cplane_eager_tx did not move ({tx})")
    if rx < 1:
        errs += 1
        print(f"rank {rank}: cplane_eager_rx did not move ({rx})")
else:
    print(f"rank {rank}: (no native plane; pvars not exercised)")

for h in handles.values():
    sess.handle_free(h)

if rank == 0 and errs == 0:
    print("No Errors")
mpi.Finalize()
sys.exit(1 if errs else 0)

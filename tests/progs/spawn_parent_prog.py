"""Process-mode dynamic-process smoke: spawn children, intercomm
collectives, merge — prints 'No Errors' (SURVEY §4 contract)."""

import os
import sys

import numpy as np

sys.path.insert(0, ".")
from mvapich2_tpu import mpi  # noqa: E402

mpi.Init()
comm = mpi.COMM_WORLD
child = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "spawn_child_prog.py")

errs = 0
inter, codes = mpi.Comm_spawn([sys.executable, child], maxprocs=2,
                              root=0, comm=comm)
if any(codes):
    errs += 1
    print(f"rank {comm.rank}: spawn errcodes {codes}")
if inter.remote_size != 2:
    errs += 1
    print(f"rank {comm.rank}: remote_size {inter.remote_size}")

# children contribute 100 + child_rank
out = inter.allreduce(np.array([comm.rank + 1], dtype=np.int64))
if int(out[0]) != 201:
    errs += 1
    print(f"rank {comm.rank}: inter allreduce {out[0]}")

merged = inter.merge(high=False)
if merged.size != comm.size + 2 or merged.rank != comm.rank:
    errs += 1
    print(f"rank {comm.rank}: merge wrong {merged.rank}/{merged.size}")
tot = merged.allreduce(np.ones(1))
if int(tot[0]) != merged.size:
    errs += 1
    print(f"rank {comm.rank}: merged allreduce {tot[0]}")

inter.barrier()
if comm.rank == 0 and errs == 0:
    print("No Errors")
mpi.Finalize()
sys.exit(1 if errs else 0)

"""Rank program: python-API correctness sweep of the NET2 node-leader
tier (coll/netcoll.py), the np > 64 sibling of flat2_sweep_prog.py.
Run at np in {65..MV2T_NET2_MAX_RANKS}.

Covers: allreduce across ops x dtypes x sizes straddling the 8 KiB
net2 small-message edge (the leaders-of-k fold band vs the rsa sched
fallback), bcast from rotating roots including non-leader ranks,
barriers, a dup'd comm (the cached leader split must re-derive), and
a tier-usage assertion (coll_level_net moved) so the sweep cannot
silently pass on the generic sched rows.

Launched via: python -m mvapich2_tpu.run -np N tests/progs/net2_sweep_prog.py
"""

import sys

import numpy as np

sys.path.insert(0, ".")
from mvapich2_tpu import mpi                        # noqa: E402

mpi.Init()
comm = mpi.COMM_WORLD
rank, size = comm.rank, comm.size
errs = 0

# int32 element counts straddling the 8 KiB net2 edge (2048 elements)
COUNTS = (1, 64, 2047, 2048, 2049)
OPS = ((mpi.SUM, "sum"), (mpi.MAX, "max"), (mpi.MIN, "min"))


def sweep(c):
    global errs
    n, r_ = c.size, c.rank
    for cnt in COUNTS:
        s = (np.arange(cnt) % 97 + r_ + 1).astype(np.int32)
        out = np.zeros(cnt, np.int32)
        c.allreduce(s, out)
        want = (np.arange(cnt) % 97 + 1).astype(np.int64) * n \
            + n * (n - 1) // 2
        if not np.array_equal(out.astype(np.int64), want):
            errs += 1
            print(f"rank {r_}: allreduce sum cnt={cnt} wrong")
    for dt in (np.int32, np.float64):
        for op, _name in OPS:
            s = (np.arange(17) % 5 + r_ + 1).astype(dt)
            out = np.zeros(17, dt)
            c.allreduce(s, out, op)
            ref = np.stack([(np.arange(17) % 5 + rr + 1).astype(dt)
                            for rr in range(n)])
            want = {mpi.SUM: ref.sum(0, dtype=dt),
                    mpi.MAX: ref.max(0), mpi.MIN: ref.min(0)}[op]
            if not np.array_equal(out, want):
                errs += 1
                print(f"rank {r_}: allreduce {_name} {dt.__name__} wrong")
    # bcast from leader (0), last rank, and mid-group non-leader roots
    for root in sorted({0, 1, n - 1, min(67, n - 1)}):
        b = np.full(33, root + 7, np.int32) if r_ == root \
            else np.zeros(33, np.int32)
        c.bcast(b, root)
        if not np.all(b == root + 7):
            errs += 1
            print(f"rank {r_}: bcast root={root} wrong")
        c.barrier()


sweep(comm)

dup = comm.dup()
sweep(dup)
dup.free()

# the net2 tier must actually have carried the small ops
from mvapich2_tpu import mpit                       # noqa: E402
from mvapich2_tpu.coll import netcoll               # noqa: E402
from mvapich2_tpu.utils.config import get_config    # noqa: E402

if get_config()["NET2"] and netcoll.net2_applicable(comm):
    moved = mpit.pvar("coll_level_net").read()
    if moved < 4:
        errs += 1
        print(f"rank {rank}: net2 tier not exercised "
              f"(coll_level_net={moved})")

total = np.zeros(1, np.int32)
comm.allreduce(np.full(1, errs, np.int32), total)
if rank == 0:
    print("No Errors" if total[0] == 0 else f"{total[0]} errors")
mpi.Finalize()
sys.exit(1 if total[0] else 0)

"""Rank program: large-message integrity over the native CMA rendezvous
(process_vm_readv pull, native/cplane.cpp PKT_RNDV_RTS_CMA).

Covers: large contiguous bidirectional sendrecv, large strided (vector)
datatype, Ssend sync semantics, truncation error, and the rndv pvar.

Launched via: python -m mvapich2_tpu.run -np 2 tests/progs/cma_rndv_prog.py
"""

import sys

import numpy as np

sys.path.insert(0, ".")
from mvapich2_tpu import mpi, mpit                  # noqa: E402
from mvapich2_tpu.core import datatype as dtmod     # noqa: E402
from mvapich2_tpu.core.errors import (              # noqa: E402
    MPIException, MPI_ERR_TRUNCATE)

mpi.Init()
comm = mpi.COMM_WORLD
rank, size = comm.rank, comm.size
peer = rank ^ 1
errs = 0

# 1. large contiguous bidirectional (rendezvous both ways)
n = 2 << 20
sbuf = np.arange(n, dtype=np.uint8) + np.uint8(rank)
rbuf = np.zeros(n, dtype=np.uint8)
comm.sendrecv(sbuf, peer, 11, rbuf, peer, 11)
expect = np.arange(n, dtype=np.uint8) + np.uint8(peer)
if not np.array_equal(rbuf, expect):
    errs += 1
    print(f"rank {rank}: large contiguous data mismatch "
          f"({int((rbuf != expect).sum())} bytes)")

# 2. large strided datatype (vector: 64k blocks of 8 doubles, stride 16)
vec = dtmod.create_vector(1 << 16, 8, 16, dtmod.DOUBLE).commit()
nelem = (1 << 16) * 16
src = np.arange(nelem, dtype=np.float64) * (rank + 1)
dst = np.zeros(nelem, dtype=np.float64)
mask = (np.arange(nelem) % 16) < 8
if rank == 0:
    comm.send(src, 1, 12, count=1, datatype=vec)
    comm.recv(dst, 1, 13, count=1, datatype=vec)
    want = np.arange(nelem, dtype=np.float64) * 2
else:
    comm.recv(dst, 0, 12, count=1, datatype=vec)
    want = np.arange(nelem, dtype=np.float64)
    comm.send(src, 0, 13, count=1, datatype=vec)
if not np.array_equal(dst[mask], want[mask]):
    errs += 1
    print(f"rank {rank}: strided rndv data mismatch")

# 3. Ssend completes only after the match (sync over CMA)
big = np.full(1 << 20, rank, dtype=np.uint8)
got = np.empty(1 << 20, dtype=np.uint8)
if rank == 0:
    comm.ssend(big, 1, 14)
    comm.recv(got, 1, 15)
else:
    comm.recv(got, 0, 14)
    comm.ssend(big, 0, 15)
if got[0] != peer or got[-1] != peer:
    errs += 1
    print(f"rank {rank}: ssend payload wrong")

# 4. truncation surfaces as an error, sender still completes
small = np.empty(1024, dtype=np.uint8)
if rank == 0:
    comm.send(big, 1, 16)          # 1 MiB into a 1 KiB buffer
else:
    try:
        comm.recv(small, 0, 16)
        errs += 1
        print("rank 1: truncation not reported")
    except MPIException as e:
        if e.error_class != MPI_ERR_TRUNCATE:
            errs += 1
            print(f"rank 1: wrong truncation class {e.error_class}")

# 5. the CMA pulls are observable via the plane pvars
u = comm.u
pch = getattr(u, "plane_channel", None)
if pch is not None and pch.plane \
        and pch._ring.lib.cp_cma_enabled(pch.plane):
    sess = mpit.pvar_session_create()
    h = sess.handle_alloc("cplane_rndv_rx")
    if sess.read(h) < 1:
        errs += 1
        print(f"rank {rank}: cplane_rndv_rx never moved")
else:
    print(f"rank {rank}: (CMA unavailable; staged rendezvous exercised)")

comm.barrier()
if rank == 0 and errs == 0:
    print("No Errors")
mpi.Finalize()
sys.exit(1 if errs else 0)

"""Chaos rank program: drive the datapath through its tiers while the
MV2T_FAULTS engine injects faults (crash-self, delay, duplicate, ...),
then prove failure CONTAINMENT: every survivor must either complete
correctly or unwind with MPIX_ERR_PROC_FAILED/MPIX_ERR_REVOKED inside
the lease deadline — never hang, never return wrong data — and then
recover via revoke + shrink and finish the remaining phases on the
shrunken comm.

Phases (MV2T_CHAOS_PHASES, default all):
  pt2pt  eager ring exchange (shm send/recv sites)
  rndv   512 KiB pairwise exchange (CMA/arena rendezvous sites)
  flat   4-byte allreduce loop (flat-slot tier; native flat_fold site)
  arena  1 MiB allreduce (arena/CMA sectioned tier)

Output per survivor:  chaos: rank=R phase=P err=C detect_s=T
plus the containment pvars, and 'No Errors' from the lowest survivor.
Run under:  mpirun -np N (with MPIEXEC_ALLOW_FAULT=1 when a crash kind
is armed; MV2T_FT_WATCHER=0 makes detection lease-only).
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, ".")
from mvapich2_tpu import mpi, mpit                     # noqa: E402
from mvapich2_tpu.core.errors import (MPIException,    # noqa: E402
                                      MPIX_ERR_PROC_FAILED,
                                      MPIX_ERR_REVOKED)

PHASES = [p for p in os.environ.get(
    "MV2T_CHAOS_PHASES", "pt2pt,rndv,flat,arena").split(",") if p]
ITERS = int(os.environ.get("MV2T_CHAOS_ITERS", "30"))

mpi.Init()
comm = mpi.COMM_WORLD
world_size = comm.size

# literal-SIGKILL mode ("<rank>:<seconds>"): the victim arms a timer
# that SIGKILLs the process mid-phase — the acceptance-criteria shape
# (no atexit, no departed-lease stamp, exactly like an OOM kill)
_kill = os.environ.get("MV2T_CHAOS_SIGKILL")
if _kill:
    _kr, _kt = _kill.split(":")
    if comm.rank == int(_kr):
        import signal
        import threading
        threading.Timer(float(_kt),
                        lambda: os.kill(os.getpid(),
                                        signal.SIGKILL)).start()

err_class = None
err_phase = None
detect_s = 0.0


def checked(phase, fn):
    """Run one faulted call; returns False once containment fired."""
    global err_class, err_phase, detect_s
    t0 = time.perf_counter()
    try:
        fn()
        return True
    except MPIException as e:
        assert e.error_class in (MPIX_ERR_PROC_FAILED, MPIX_ERR_REVOKED), \
            f"unexpected error class {e.error_class}: {e}"
        err_class = e.error_class
        err_phase = phase
        detect_s = time.perf_counter() - t0
        return False


def run_phases(c, phases, iters=ITERS):
    n = c.size
    for phase in phases:
        if phase == "pt2pt" and n > 1:
            small = np.full(8, float(c.rank))
            inbuf = np.zeros(8)
            for _ in range(iters):
                def ring():
                    req = c.isend(small, dest=(c.rank + 1) % n, tag=11)
                    st = c.recv(inbuf, source=(c.rank - 1) % n, tag=11)
                    req.wait()
                    assert inbuf[0] == float((c.rank - 1) % n), \
                        f"pt2pt payload corrupt: {inbuf[0]}"
                    assert st.source == (c.rank - 1) % n
                if not checked(phase, ring):
                    return False
        elif phase == "rndv" and n > 1:
            # ring-shaped so EVERY rank depends (transitively) on every
            # other — a pairwise scheme would leave non-partner
            # survivors untouched by the failure and desynchronized
            # from the recovery collective
            big = np.full(1 << 16, float(c.rank))      # 512 KiB f64
            out = np.zeros(1 << 16)
            src = (c.rank - 1) % n
            for _ in range(max(2, iters // 10)):
                def xchg():
                    req = c.isend(big, dest=(c.rank + 1) % n, tag=13)
                    c.recv(out, source=src, tag=13)
                    req.wait()
                    assert out[0] == float(src) \
                        and out[-1] == float(src), \
                        f"rndv payload corrupt: {out[0]}/{out[-1]}"
                if not checked(phase, xchg):
                    return False
        elif phase == "flat" and n > 1:
            s = np.full(1, np.int32(c.rank + 1))
            r = np.zeros(1, np.int32)
            expect = n * (n + 1) // 2
            for _ in range(iters):
                def tiny():
                    c.allreduce(s, r)
                    assert r[0] == expect, \
                        f"flat allreduce corrupt: {r[0]} != {expect}"
                if not checked(phase, tiny):
                    return False
        elif phase == "arena" and n > 1:
            s = np.ones(1 << 17)                        # 1 MiB f64
            r = np.zeros(1 << 17)
            for _ in range(max(2, iters // 10)):
                def big_ar():
                    c.allreduce(s, r)
                    assert r[0] == float(n) and r[-1] == float(n), \
                        f"arena allreduce corrupt: {r[0]}/{r[-1]}"
                if not checked(phase, big_ar):
                    return False
    return True


clean = run_phases(comm, PHASES)
final = comm
if not clean:
    # containment fired: recover (revoke -> ack -> shrink) and prove the
    # shrunken comm works by re-running the remaining tiers on it
    if not comm.revoked:
        comm.revoke()
    comm.failure_ack()
    final = comm.shrink()
    redo = [p for p in PHASES if p in ("pt2pt", "flat")]
    assert run_phases(final, redo, iters=min(ITERS, 30)), \
        "second failure during recovery"

pv = {n: int(mpit.pvar(n).read())
      for n in ("dead_peer_detections", "wait_deadline_trips",
                "revokes_propagated", "faults_injected")}
print(f"chaos: rank={comm.rank} phase={err_phase} err={err_class} "
      f"detect_s={detect_s:.2f} shrunk={final.size} "
      f"dead_peer_detections={pv['dead_peer_detections']} "
      f"wait_deadline_trips={pv['wait_deadline_trips']} "
      f"revokes_propagated={pv['revokes_propagated']} "
      f"faults_injected={pv['faults_injected']}", flush=True)
if final.rank == 0:
    print("No Errors")
mpi.Finalize()
sys.exit(0)

"""One-sided remote-DMA engine (ops/pallas_rma) — interpret-mode
correctness sweep on the virtual CPU mesh.

Put/Get/Accumulate are exact kernels: the sweep asserts element
equality (bit equality for integer data) against the window semantics
for every op x dtype (f32/bf16/i32) x chunk-boundary offset/shape x
mesh width in {2,4,8}, that only the addressed pair's shard changes,
and that the quantized accumulate honors the pallas_quant
``declared_bound`` error contract. Tier selection
(``planned_rma_tier``) is unit-tested against the coll/tuning
``dev_rma_*`` edges; the end-to-end DeviceWin dispatch rides in
tests/test_device_rma.py.
"""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from mvapich2_tpu.ops import pallas_rma  # noqa: E402
from mvapich2_tpu.parallel import make_mesh  # noqa: E402
from mvapich2_tpu.parallel.mesh import shard_map  # noqa: E402
from mvapich2_tpu.utils.config import get_config  # noqa: E402

DTYPES = {"f32": jnp.float32, "bf16": jnp.bfloat16, "i32": jnp.int32}


def _reload(**env):
    for k, v in env.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    get_config().reload()


@pytest.fixture(autouse=True)
def _clean_env():
    yield
    _reload(MV2T_QUANT_COLL=None, MV2T_RMA_CHUNK_BYTES=None,
            MV2T_ICI_INTERPRET=None, MV2T_DEV_RMA_RDMA_MIN=None,
            MV2T_DEV_RMA_QUANT_MIN=None)


_MESHES = {}


def _mesh(nd):
    if nd not in _MESHES:
        _MESHES[nd] = make_mesh((nd,), ("x",), jax.devices()[:nd])
    return _MESHES[nd]


def _shard(mesh, arr):
    return jax.device_put(arr, NamedSharding(mesh, P("x")))


def _f32(a):
    return np.asarray(a.astype(jnp.float32)) if a.dtype == jnp.bfloat16 \
        else np.asarray(a)


def _run(nd, prog, win):
    mesh = _mesh(nd)
    f = shard_map(prog, mesh=mesh, in_specs=(P("x"),), out_specs=P("x"),
                  check_vma=False)
    return jax.jit(f)(_shard(mesh, win))


def _win_rows(nd, n, dtype):
    """Distinct per-rank window contents, exactly representable in
    every swept dtype (small integers)."""
    base = jnp.arange(n, dtype=jnp.float32) % 13
    rows = jnp.stack([base + 20.0 * r for r in range(nd)])
    return rows.astype(dtype)


# ---------------------------------------------------------------------------
# put / get / accumulate x dtype x mesh width, chunk-boundary shapes
# ---------------------------------------------------------------------------

# 16-byte chunks -> 4 f32/i32 or 8 bf16 elems per chunk; n spans ~2.5
# chunks so the sweep always crosses a chunk boundary and ends on a
# partial chunk, and disp=3 misaligns the window landing.
_CB = 16


def _nelems(dtype):
    epc = _CB // np.dtype(dtype).itemsize
    return 2 * epc + epc // 2


@pytest.mark.parametrize("nd,dt", [(2, "f32"), (4, "bf16"), (8, "i32"),
                                   (8, "f32")])
def test_put_pair_only(nd, dt):
    dtype = DTYPES[dt]
    n = _nelems(dtype)
    N, disp, origin, target = n + 8, 3, nd - 2, nd - 1
    win = _win_rows(nd, N, dtype)
    src = (jnp.arange(n, dtype=jnp.float32) + 1.0).astype(dtype)

    def prog(w_row):
        return pallas_rma.rma_put(src, w_row[0], "x", nd, origin, target,
                                  disp, chunk_bytes=_CB,
                                  interpret=True)[None, :]

    out = _run(nd, prog, win)
    exp = _f32(win).copy()
    exp[target, disp:disp + n] = _f32(src)
    if dt == "i32":
        assert np.array_equal(np.asarray(out), exp.astype(np.int32))
    else:
        np.testing.assert_allclose(_f32(out), exp)


@pytest.mark.parametrize("nd,dt", [(2, "i32"), (4, "f32"), (8, "bf16")])
def test_get_origin_only(nd, dt):
    dtype = DTYPES[dt]
    n = _nelems(dtype)
    N, disp, origin, target = n + 8, 5, 0, nd - 1
    win = _win_rows(nd, N, dtype)

    def prog(w_row):
        return pallas_rma.rma_get(w_row[0], n, "x", nd, origin, target,
                                  disp, chunk_bytes=_CB,
                                  interpret=True)[None, :]

    out = _run(nd, prog, win)
    exp = np.zeros((nd, n), np.float32)
    exp[origin] = _f32(win)[target, disp:disp + n]
    if dt == "i32":
        assert np.array_equal(np.asarray(out), exp.astype(np.int32))
    else:
        np.testing.assert_allclose(_f32(out), exp)


@pytest.mark.parametrize("nd,dt", [(2, "bf16"), (4, "i32"), (8, "f32")])
def test_accumulate_exact(nd, dt):
    dtype = DTYPES[dt]
    n = _nelems(dtype)
    N, disp, origin, target = n + 8, 2, 1, 0
    win = _win_rows(nd, N, dtype)
    src = (jnp.arange(n, dtype=jnp.float32) % 7 + 1.0).astype(dtype)

    def prog(w_row):
        return pallas_rma.rma_accumulate(src, w_row[0], "x", nd, origin,
                                         target, disp, chunk_bytes=_CB,
                                         interpret=True)[None, :]

    out = _run(nd, prog, win)
    exp = _f32(win).copy()
    exp[target, disp:disp + n] += _f32(src)
    if dt == "i32":
        assert np.array_equal(np.asarray(out), exp.astype(np.int32))
    else:
        np.testing.assert_allclose(_f32(out), exp)


@pytest.mark.parametrize("n,disp,cb", [
    (8, 0, 16),     # exact chunk multiple at the window base
    (3, 1, 16),     # single partial chunk
    (4, 12, 16),    # n == chunk, landing flush with the window end
    (21, 2, 8),     # many (11) tiny chunks, partial tail
])
def test_put_chunk_boundary_shapes(n, disp, cb):
    nd = 4
    win = _win_rows(nd, 16 + 21, jnp.float32)
    src = jnp.arange(n, dtype=jnp.float32) + 0.5

    def prog(w_row):
        return pallas_rma.rma_put(src, w_row[0], "x", nd, 3, 1, disp,
                                  chunk_bytes=cb,
                                  interpret=True)[None, :]

    out = np.asarray(_run(nd, prog, win))
    exp = np.asarray(win).copy()
    exp[1, disp:disp + n] = np.asarray(src)
    np.testing.assert_allclose(out, exp)


# ---------------------------------------------------------------------------
# quantized accumulate: the declared_bound error contract
# ---------------------------------------------------------------------------

def test_accumulate_quantized_within_declared_bound():
    from mvapich2_tpu.ops.pallas_quant import declared_bound
    _reload(MV2T_QUANT_COLL="q8:1e-1")
    nd, n, disp = 8, 256, 128
    win = jnp.ones((nd, 512), jnp.float32)
    src = jnp.linspace(-3.0, 5.0, n, dtype=jnp.float32)

    def prog(w_row):
        return pallas_rma.rma_accumulate(
            src, w_row[0], "x", nd, 4, 7, disp, quantized=True,
            chunk_bytes=512, interpret=True)[None, :]

    out = np.asarray(_run(nd, prog, win))
    exp = np.ones((nd, 512), np.float32)
    exp[7, disp:disp + n] += np.asarray(src)
    # an RMA accumulate is one quantization hop: per element the error
    # is within declared_bound(1, wire) of the block absmax
    bound = declared_bound(1, "q8") * np.abs(np.asarray(src)).max()
    assert np.abs(out[7] - exp[7]).max() <= bound + 1e-6
    # non-target shards untouched (the identity fold is exact: zeros
    # encode to zeros)
    others = [r for r in range(nd) if r != 7]
    np.testing.assert_array_equal(out[others], exp[others])


def test_accumulate_quantized_rejects_non_block_multiple():
    _reload(MV2T_QUANT_COLL="q8:1e-1")
    win = _win_rows(2, 300, jnp.float32)
    src = jnp.ones((130,), jnp.float32)
    with pytest.raises(ValueError, match="block-multiple"):
        def prog(w_row):
            return pallas_rma.rma_accumulate(
                src, w_row[0], "x", 2, 0, 1, 0, quantized=True,
                interpret=True)[None, :]
        _run(2, prog, win)


# ---------------------------------------------------------------------------
# tier selection (planned_rma_tier x the dev_rma_* tuning edges)
# ---------------------------------------------------------------------------

def test_planned_tier_rdma_for_contiguous():
    tier, reason = pallas_rma.planned_rma_tier(
        "put", 4096, jnp.float32, True, interpret=True)
    assert (tier, reason) == ("rdma", None)


def test_planned_tier_epoch_reasons():
    cases = [
        (("put", 4096, jnp.float32, False), "noncontig"),
        (("get", 4096, jnp.complex64, True), "dtype"),
        (("put", 0, jnp.float32, True), "size"),
    ]
    for args, want in cases:
        tier, reason = pallas_rma.planned_rma_tier(*args, interpret=True)
        assert (tier, reason) == ("epoch", want), args


def test_planned_tier_size_edge_cvar():
    _reload(MV2T_DEV_RMA_RDMA_MIN="1024")
    tier, reason = pallas_rma.planned_rma_tier(
        "put", 512, jnp.float32, True, interpret=True)
    assert (tier, reason) == ("epoch", "size")
    tier, reason = pallas_rma.planned_rma_tier(
        "put", 2048, jnp.float32, True, interpret=True)
    assert (tier, reason) == ("rdma", None)


def test_planned_tier_quant_bin():
    _reload(MV2T_QUANT_COLL="q8:1e-1", MV2T_DEV_RMA_QUANT_MIN="1024")
    # a big block-multiple f32 accumulate lands in the quant bin
    tier, _ = pallas_rma.planned_rma_tier(
        "acc", 1 << 20, jnp.float32, True, interpret=True,
        num_devices=8, count=(1 << 20) // 4)
    assert tier == "quant"
    # puts never quantize; int accumulates degrade to the exact tier
    tier, _ = pallas_rma.planned_rma_tier(
        "put", 1 << 20, jnp.float32, True, interpret=True,
        num_devices=8, count=(1 << 20) // 4)
    assert tier == "rdma"
    tier, _ = pallas_rma.planned_rma_tier(
        "acc", 1 << 20, jnp.int32, True, interpret=True,
        num_devices=8, count=(1 << 20) // 4)
    assert tier == "rdma"
    # budget off -> exact rdma
    _reload(MV2T_QUANT_COLL=None, MV2T_DEV_RMA_QUANT_MIN="1024")
    tier, _ = pallas_rma.planned_rma_tier(
        "acc", 1 << 20, jnp.float32, True, interpret=True,
        num_devices=8, count=(1 << 20) // 4)
    assert tier == "rdma"


def test_acc_quant_ok_gates():
    _reload(MV2T_QUANT_COLL="q8:1e-1")
    assert pallas_rma.acc_quant_ok(jnp.float32, 512, 8)
    assert not pallas_rma.acc_quant_ok(jnp.int32, 512, 8)
    assert not pallas_rma.acc_quant_ok(jnp.float32, 130, 8)
    _reload(MV2T_QUANT_COLL="q8:1e-4")   # budget below one-hop bound
    assert not pallas_rma.acc_quant_ok(jnp.float32, 512, 8)


def test_rma_chunk_cvar_inherits_ici_edge():
    _reload(MV2T_RMA_CHUNK_BYTES=None)
    from mvapich2_tpu.coll.tuning import kernel_param_cv
    assert pallas_rma._cfg_chunk_elems(jnp.float32, None) == \
        kernel_param_cv("ici_chunk_bytes", "ICI_CHUNK_BYTES") // 4
    _reload(MV2T_RMA_CHUNK_BYTES="256")
    assert pallas_rma._cfg_chunk_elems(jnp.float32, None) == 64

"""Resource-manager glue + CPU binding (runtime/rm.py, utils/affinity.py
— analogs of src/pm/mpirun/src/{slurm,pbs} and hwloc_bind.c)."""

import os
import subprocess
import sys

import pytest

from mvapich2_tpu.runtime.hostfile import HostSpec
from mvapich2_tpu.runtime.rm import (detect_rm_rank,
                                     expand_slurm_nodelist, rm_hosts)
from mvapich2_tpu.utils.affinity import slice_for

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_slurm_nodelist_grammar():
    assert expand_slurm_nodelist("tpu[001-003,007],login1") == [
        "tpu001", "tpu002", "tpu003", "tpu007", "login1"]
    assert expand_slurm_nodelist("n1,n2") == ["n1", "n2"]
    assert expand_slurm_nodelist("host[9-11]") == ["host9", "host10",
                                                   "host11"]
    assert expand_slurm_nodelist("solo") == ["solo"]
    # suffix after a bracket group, and multiple groups per name
    assert expand_slurm_nodelist("c[1-2]n1") == ["c1n1", "c2n1"]
    assert expand_slurm_nodelist("a[1-2]b[3-4]") == [
        "a1b3", "a1b4", "a2b3", "a2b4"]


RM_VARS = ("SLURM_PROCID", "SLURM_NTASKS", "SLURM_JOB_NODELIST",
           "SLURM_TASKS_PER_NODE", "PBS_TASKNUM", "PBS_NP",
           "PBS_NODEFILE", "PMI_RANK", "PMI_SIZE")


def _clear_rm(monkeypatch):
    for v in RM_VARS:
        monkeypatch.delenv(v, raising=False)


def test_detect_rm_rank(monkeypatch):
    _clear_rm(monkeypatch)
    assert detect_rm_rank() is None
    monkeypatch.setenv("SLURM_PROCID", "3")
    monkeypatch.setenv("SLURM_NTASKS", "8")
    assert detect_rm_rank() == (3, 8)
    monkeypatch.delenv("SLURM_PROCID")
    monkeypatch.delenv("SLURM_NTASKS")
    monkeypatch.setenv("PBS_TASKNUM", "2")   # 1-based
    monkeypatch.setenv("PBS_NP", "4")
    assert detect_rm_rank() == (1, 4)


def test_rm_hosts_slurm(monkeypatch):
    _clear_rm(monkeypatch)
    monkeypatch.setenv("SLURM_JOB_NODELIST", "n[1-3]")
    monkeypatch.setenv("SLURM_TASKS_PER_NODE", "4(x2),2")
    hosts = rm_hosts()
    assert hosts == [HostSpec("n1", 4), HostSpec("n2", 4),
                     HostSpec("n3", 2)]


def test_rm_hosts_pbs(monkeypatch, tmp_path):
    _clear_rm(monkeypatch)
    nf = tmp_path / "nodes"
    nf.write_text("a\na\nb\n")
    monkeypatch.setenv("PBS_NODEFILE", str(nf))
    assert rm_hosts() == [HostSpec("a", 2), HostSpec("b", 1)]


def test_affinity_slices():
    cores = list(range(8))
    # bunch: adjacent slices
    assert slice_for(0, 2, cores, "bunch") == {0, 1, 2, 3}
    assert slice_for(1, 2, cores, "bunch") == {4, 5, 6, 7}
    # remainder to low ranks
    assert slice_for(0, 3, cores, "bunch") == {0, 1, 2}
    assert slice_for(2, 3, cores, "bunch") == {6, 7}
    # scatter: strided
    assert slice_for(0, 2, cores, "scatter") == {0, 2, 4, 6}
    assert slice_for(1, 2, cores, "scatter") == {1, 3, 5, 7}
    # oversubscription: one core each, wrapped
    assert slice_for(9, 12, cores, "bunch") == {1}
    # disjoint + complete cover
    got = set()
    for r in range(3):
        s = slice_for(r, 3, cores, "bunch")
        assert not (got & s)
        got |= s
    assert got == set(cores)


@pytest.mark.skipif(not hasattr(os, "sched_getaffinity"),
                    reason="no sched_getaffinity")
def test_binding_applied_end_to_end(tmp_path):
    """Ranks launched with MV2T_ENABLE_AFFINITY get disjoint masks when
    cores allow, and the job still runs collectives."""
    ncores = len(os.sched_getaffinity(0))
    prog = tmp_path / "aff_prog.py"
    prog.write_text(
        "import os, sys\n"
        "sys.path.insert(0, %r)\n"
        "from mvapich2_tpu import mpi\n"
        "import numpy as np\n"
        "mpi.Init()\n"
        "c = mpi.COMM_WORLD\n"
        "mask = sorted(os.sched_getaffinity(0))\n"
        "out = c.allreduce(np.array([float(len(mask))]))\n"
        "if c.rank == 0:\n"
        "    print('MASKSUM', out[0])\n"
        "    print('No Errors')\n"
        "mpi.Finalize()\n" % REPO)
    r = subprocess.run(
        [sys.executable, "-m", "mvapich2_tpu.run", "-np", "2",
         sys.executable, str(prog)],
        cwd=REPO, capture_output=True, text=True, timeout=120,
        env={**os.environ, "MV2T_ENABLE_AFFINITY": "1"})
    assert r.returncode == 0, r.stderr[-400:]
    assert "No Errors" in r.stdout
    # with >=2 cores the two ranks' masks are disjoint slices covering
    # all cores: the mask sizes sum to ncores
    if ncores >= 2:
        masksum = float(r.stdout.split("MASKSUM")[1].split()[0])
        assert masksum == ncores

"""Device-path (ICI channel) tests on the 8-device virtual CPU mesh —
the XLA-native collective layer that replaces the reference's transport."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from mvapich2_tpu import ops  # noqa: E402
from mvapich2_tpu.parallel import MeshComm, make_mesh, mesh_shape_for  # noqa: E402


@pytest.fixture(scope="module")
def comm8():
    return MeshComm(make_mesh((8,), ("x",)))


def test_mesh_shape_for():
    assert mesh_shape_for(8, 2) == (2, 4)
    assert mesh_shape_for(16, 2) == (4, 4)
    assert mesh_shape_for(7, 2) == (1, 7)
    assert mesh_shape_for(8, 1) == (8,)


def test_allreduce_psum(comm8):
    x = jnp.arange(32, dtype=jnp.float32)
    out = comm8.run(lambda s: comm8.allreduce(s), x)
    # each shard of 4 elems summed over... psum sums the *shards*; with
    # out_specs P('x') each shard holds the sum of all 8 shards' values
    expected = x.reshape(8, 4).sum(axis=0)
    got = np.asarray(out).reshape(8, 4)
    for blk in got:
        np.testing.assert_allclose(blk, expected)


def test_allreduce_max(comm8):
    x = jnp.arange(8, dtype=jnp.float32)
    out = comm8.run(lambda s: comm8.allreduce(s, op="max"), x)
    assert np.asarray(out).max() == 7.0
    assert (np.asarray(out) == 7.0).all()


def test_bcast_from_root(comm8):
    x = jnp.arange(8, dtype=jnp.float32) * 10
    out = comm8.run(lambda s: comm8.bcast(s, root=3), x)
    np.testing.assert_allclose(np.asarray(out), 30.0)


def test_all_gather(comm8):
    x = jnp.arange(8, dtype=jnp.int32)
    out = comm8.run(lambda s: comm8.all_gather(s, tiled=True), x,
                    out_specs=P("x"))
    # every shard gathers the full vector; tiled output is [8*8] globally
    got = np.asarray(out).reshape(8, 8)
    for row in got:
        np.testing.assert_array_equal(row, np.arange(8))


def test_reduce_scatter(comm8):
    # each shard holds [8] -> psum_scatter leaves each shard sum-block
    x = jnp.tile(jnp.arange(8, dtype=jnp.float32), (8,))
    out = comm8.run(lambda s: comm8.reduce_scatter(s), x)
    np.testing.assert_allclose(np.asarray(out), np.arange(8) * 8)


def test_all_to_all(comm8):
    # shard i holds blocks destined to each peer: value i*8+j for peer j
    x = jnp.arange(64, dtype=jnp.int32)
    out = comm8.run(lambda s: comm8.all_to_all(s), x)
    got = np.asarray(out).reshape(8, 8)
    expected = np.arange(64).reshape(8, 8).T
    np.testing.assert_array_equal(got, expected)


def test_ring_shift(comm8):
    x = jnp.arange(8, dtype=jnp.int32)
    out = comm8.run(lambda s: comm8.ring_shift(s, 1), x)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.roll(np.arange(8), 1))


def test_halo_exchange_periodic(comm8):
    # global [32] split into 8 shards of 4; halo width 1
    x = jnp.arange(32, dtype=jnp.float32)
    out = comm8.run(lambda s: comm8.halo_exchange(s, halo=1), x,
                    out_specs=P("x"))
    got = np.asarray(out).reshape(8, 6)
    g = np.arange(32, dtype=np.float32).reshape(8, 4)
    for i in range(8):
        np.testing.assert_allclose(got[i, 0], g[(i - 1) % 8, -1])
        np.testing.assert_allclose(got[i, 1:-1], g[i])
        np.testing.assert_allclose(got[i, -1], g[(i + 1) % 8, 0])


def test_halo_exchange_nonperiodic(comm8):
    x = jnp.arange(32, dtype=jnp.float32)
    out = comm8.run(lambda s: comm8.halo_exchange(s, halo=1,
                                                  periodic=False), x,
                    out_specs=P("x"))
    got = np.asarray(out).reshape(8, 6)
    assert got[0, 0] == 0.0          # no left neighbor
    assert got[7, -1] == 0.0         # no right neighbor


def test_scan_axis(comm8):
    x = jnp.ones(8, dtype=jnp.float32)
    out = comm8.run(lambda s: comm8.scan(s), x, out_specs=P("x"))
    np.testing.assert_allclose(np.asarray(out), np.arange(1, 9))


@pytest.mark.slow
def test_ring_allreduce_manual_matches_psum(comm8):
    x = jnp.arange(80, dtype=jnp.float32).reshape(8, 10)

    def fused(s):
        return ops.allreduce(s, "x")

    def manual(s):
        return ops.ring_allreduce_manual(s, "x")

    a = comm8.run(fused, x.reshape(-1), out_specs=P("x"))
    b = comm8.run(manual, x.reshape(-1), out_specs=P("x"))
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_two_axis_hierarchy():
    """2-level analog: reduce over intra-'host' axis then inter axis
    equals flat psum over both (the shmem+leader identity)."""
    mesh = make_mesh((2, 4), ("dcn", "host"))
    comm = MeshComm(mesh, "host")
    x = jnp.arange(16, dtype=jnp.float32)

    def two_level(s):
        intra = ops.allreduce(s, "host")
        return ops.allreduce(intra, "dcn")

    def flat(s):
        return ops.allreduce(s, ("dcn", "host"))

    a = comm.run(two_level, x, in_specs=(P(("dcn", "host")),),
                 out_specs=P(("dcn", "host")))
    b = comm.run(flat, x, in_specs=(P(("dcn", "host")),),
                 out_specs=P(("dcn", "host")))
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_moe_shuffle_roundtrip(comm8):
    x = jnp.arange(64, dtype=jnp.float32)

    def roundtrip(s):
        return ops.moe_shuffle(ops.moe_shuffle(s, "x"), "x")

    out = comm8.run(roundtrip, x, out_specs=P("x"))
    np.testing.assert_allclose(np.asarray(out), np.arange(64))


def test_under_jit_compiles_once(comm8):
    x = jnp.arange(8, dtype=jnp.float32)

    @jax.jit
    def step(v):
        return comm8.run(lambda s: comm8.allreduce(s * 2.0), v)

    out = step(x)
    np.testing.assert_allclose(np.asarray(out)[0], np.arange(8).sum() * 2)


# ---------------------------------------------------------------------------
# pallas ring kernels (TPU interpret mode with race detection)
# ---------------------------------------------------------------------------

def _interp():
    # race-detecting interpreter when this jax has it, plain interpret
    # otherwise (ops/_compat owns the version seam)
    from mvapich2_tpu.ops._compat import interpret_params
    return interpret_params(detect_races=True)


def test_pallas_ring_all_gather(comm8):
    from mvapich2_tpu.ops import pallas_ring
    x = jnp.arange(64, dtype=jnp.float32)
    ip = _interp()
    out = comm8.run(lambda s: pallas_ring.ring_all_gather(s, "x", 8,
                                                          interpret=ip),
                    x, out_specs=P("x"))
    got = np.asarray(out).reshape(8, 64)
    for row in got:
        np.testing.assert_array_equal(row, np.arange(64))


def test_pallas_ring_all_reduce(comm8):
    from mvapich2_tpu.ops import pallas_ring
    x = jnp.arange(64, dtype=jnp.float32)
    ip = _interp()
    out = comm8.run(lambda s: pallas_ring.ring_all_reduce(s, "x", 8,
                                                          interpret=ip),
                    x, out_specs=P("x"))
    got = np.asarray(out).reshape(8, 8)
    expected = np.arange(64, dtype=np.float32).reshape(8, 8).sum(axis=0)
    for row in got:
        np.testing.assert_allclose(row, expected)


def test_pallas_ring_all_reduce_2d(comm8):
    from mvapich2_tpu.ops import pallas_ring
    x = jnp.arange(8 * 16 * 4, dtype=jnp.float32).reshape(8 * 16, 4)
    ip = _interp()
    out = comm8.run(lambda s: pallas_ring.ring_all_reduce(s, "x", 8,
                                                          interpret=ip),
                    x, out_specs=P("x"))
    got = np.asarray(out).reshape(8, 16, 4)
    expected = np.arange(8 * 16 * 4, dtype=np.float32).reshape(8, 16, 4) \
        .sum(axis=0)
    for blk in got:
        np.testing.assert_allclose(blk, expected)


def test_pallas_fallback_nondivisible(comm8):
    """Non-divisible shapes take the lax.psum fallback (the crossover)."""
    from mvapich2_tpu.ops import pallas_ring
    x = jnp.arange(8 * 5, dtype=jnp.float32)  # shard 5 elems, 5 % 8 != 0
    out = comm8.run(lambda s: pallas_ring.ring_all_reduce(s, "x", 8), x)
    expected = np.arange(40, dtype=np.float32).reshape(8, 5).sum(axis=0)
    np.testing.assert_allclose(np.asarray(out).reshape(8, 5)[0], expected)

"""Nonblocking + persistent device collectives on the NBC DAG (ISSUE 18).

The VERDICT-driving contract: i-collectives on a mesh-bound comm route
to the device tier as NBC-DAG schedules (deposit CALL -> per-segment
POLL vertices -> completion CALL) whose results are bit-identical to
the blocking device path; calls the channel cannot route count
dev_coll_fallback_nbc and take the host schedule unchanged; the
MPI_*_init persistent surface pre-warms the program build through the
daemon exec-cache seam so warm starts skip the compile; a rank dying
mid-flight unwinds survivor DAGs with MPIX_ERR_PROC_FAILED and leaks
no schedule state.
"""

import os
import shutil
import tempfile
import time

import numpy as np
import pytest

from mvapich2_tpu import mpit
from mvapich2_tpu.core.errors import MPIException, MPIX_ERR_PROC_FAILED
from mvapich2_tpu.runtime.universe import run_ranks
from mvapich2_tpu.utils.config import get_config

N_RANKS = 8


def _reload(**env):
    for k, v in env.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    get_config().reload()


@pytest.fixture(autouse=True)
def _clean_env():
    yield
    _reload(MV2T_DEVICE_COLL_MIN_BYTES=None,
            MV2T_DEVICE_NBC_SEG_BYTES=None,
            MV2T_DEVICE_NBC_MAX_SEGS=None,
            MV2T_ALLREDUCE_ALGO=None, MV2T_METRICS=None)


@pytest.fixture()
def ddir():
    d = tempfile.mkdtemp(prefix="mv2t-devnbc-test-")
    _reload(MV2T_DAEMON_SPAWN="0")
    yield d
    _reload(MV2T_DAEMON_SPAWN=None, MV2T_DAEMON=None,
            MV2T_DAEMON_DIR=None, MV2T_DAEMON_EXEC_CACHE=None)
    shutil.rmtree(d, ignore_errors=True)


def _count_matrix(p, shape):
    """Deterministic skewed count matrices every rank can rebuild."""
    if shape == "uniform":
        return [[3] * p for _ in range(p)]
    if shape == "zero":                 # rank 0 sends nothing at all
        return [[0] * p if i == 0 else [(i + j) % 4 for j in range(p)]
                for i in range(p)]
    return [[(i + 2 * j) % 3 for j in range(p)] for i in range(p)]


def _v_bufs(p, r, counts, dtype):
    """(sendbuf, scounts, rcounts, expect) for rank r: peer j's payload
    is arange(sender*1000 + receiver*100, ...) — position-exact."""
    scounts = list(counts[r])
    rcounts = [counts[j][r] for j in range(p)]
    send = np.concatenate(
        [np.arange(r * 1000 + j * 100, r * 1000 + j * 100 + c)
         for j, c in enumerate(scounts)] or [np.zeros(0)]).astype(dtype)
    expect = np.concatenate(
        [np.arange(j * 1000 + r * 100, j * 1000 + r * 100 + c)
         for j, c in enumerate(rcounts)] or [np.zeros(0)]).astype(dtype)
    return send, scounts, rcounts, expect


# ---------------------------------------------------------------------------
# tentpole: i-collectives ride the device NBC DAG, results bit-identical
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("nr", [2, 4, 8])
def test_inbc_device_route_bit_identical(nr):
    """iallreduce/ialltoall/ialltoallv on int data route device
    (req.device_nbc), overlap a local compute phase between issue and
    wait, and land bit-identical results; the DAG engine issues their
    vertices (nbc_vertices_issued) and the segmented allreduce launches
    multiple device segments (dev_nbc_segments)."""
    _reload(MV2T_DEVICE_COLL_MIN_BYTES="1",
            MV2T_DEVICE_NBC_SEG_BYTES="256")
    v0 = mpit.pvar("nbc_vertices_issued").read()
    s0 = mpit.pvar("dev_nbc_segments").read()
    routed = {"ar": [], "a2a": [], "a2av": []}

    def app(comm):
        p, r = comm.size, comm.rank
        # iallreduce: 2048B int32 -> 8 segments at 256B
        x = np.arange(512, dtype=np.int32) + r
        out = np.zeros_like(x)
        req = comm.iallreduce(x, out)
        routed["ar"].append(getattr(req, "device_nbc", False))
        local = x * 2                    # overlapped compute
        req.wait()
        blocking = comm.allreduce(x)     # the blocking device path
        np.testing.assert_array_equal(out, blocking)
        np.testing.assert_array_equal(
            out, np.arange(512, dtype=np.int32) * p + sum(range(p)))
        assert local[1] == x[1] * 2
        # ialltoall
        send = np.array([r * p + j for j in range(p)],
                        np.int32).repeat(8)
        recv = np.zeros_like(send)
        req = comm.ialltoall(send, recv)
        routed["a2a"].append(getattr(req, "device_nbc", False))
        req.wait()
        np.testing.assert_array_equal(
            recv, np.array([s * p + r for s in range(p)],
                           np.int32).repeat(8))
        # ialltoallv: skewed counts, dense displs
        counts = _count_matrix(p, "skew")
        send, scounts, rcounts, expect = _v_bufs(p, r, counts, np.int32)
        recv = np.zeros(sum(rcounts), np.int32)
        req = comm.ialltoallv(send, scounts, None, recv, rcounts, None)
        routed["a2av"].append(getattr(req, "device_nbc", False))
        req.wait()
        np.testing.assert_array_equal(recv, expect)

    run_ranks(nr, app, device_mesh=True)
    for k, v in routed.items():
        assert v and all(v), f"{k} did not route device: {v}"
    assert mpit.pvar("nbc_vertices_issued").read() > v0
    assert mpit.pvar("dev_nbc_segments").read() >= s0 + 8 + 1 + 1


def test_nonroutable_icoll_counts_fallback():
    """float64 does not lower (x64 off): the i-collective counts
    dev_coll_fallback_nbc, takes the host schedule, and is still
    correct."""
    _reload(MV2T_DEVICE_COLL_MIN_BYTES="1")
    f0 = mpit.pvar("dev_coll_fallback_nbc").read()
    routed = []

    def app(comm):
        x = np.arange(64, dtype=np.float64) + comm.rank
        out = np.zeros_like(x)
        req = comm.iallreduce(x, out)
        routed.append(getattr(req, "device_nbc", False))
        req.wait()
        np.testing.assert_array_equal(
            out, np.arange(64, dtype=np.float64) * comm.size
            + sum(range(comm.size)))

    run_ranks(4, app, device_mesh=True)
    assert not any(routed)
    assert mpit.pvar("dev_coll_fallback_nbc").read() >= f0 + 4


# ---------------------------------------------------------------------------
# blocking alltoall(v) correctness sweep through the coll API
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", [np.int32, np.float32, np.uint16])
@pytest.mark.parametrize("shape", ["uniform", "skew", "zero"])
def test_blocking_alltoallv_sweep(dtype, shape):
    _reload(MV2T_DEVICE_COLL_MIN_BYTES="1")

    def app(comm):
        p, r = comm.size, comm.rank
        counts = _count_matrix(p, shape)
        send, scounts, rcounts, expect = _v_bufs(p, r, counts, dtype)
        sd = np.concatenate(([0], np.cumsum(scounts)[:-1])).tolist()
        rd = np.concatenate(([0], np.cumsum(rcounts)[:-1])).tolist()
        recv = np.zeros(max(1, sum(rcounts)), dtype)
        comm.alltoallv(send, scounts, sd, recv, rcounts, rd)
        np.testing.assert_array_equal(recv[:sum(rcounts)], expect)

    run_ranks(4, app, device_mesh=True)


@pytest.mark.parametrize("c", [1, 16, 33])   # straddles chunk edges
def test_blocking_alltoall_shapes(c):
    _reload(MV2T_DEVICE_COLL_MIN_BYTES="1")

    def app(comm):
        p, r = comm.size, comm.rank
        send = np.array([r * p + j for j in range(p)],
                        np.int32).repeat(c)
        got = comm.alltoall(send)
        np.testing.assert_array_equal(
            got, np.array([s * p + r for s in range(p)],
                          np.int32).repeat(c))

    run_ranks(N_RANKS, app, device_mesh=True)


# ---------------------------------------------------------------------------
# persistent collectives: exec-cache pre-warm + cheap starts
# ---------------------------------------------------------------------------

def test_persistent_allreduce_warm_start_exec_cache(ddir):
    """MPI_Allreduce_init pre-warms the device program through the
    daemon exec-cache seam: the cold job's init BUILDS and caches
    (exec_cache_misses moves), the second job's init fetches the
    serialized executable instead of compiling (exec_cache_hits moves
    — the measurably-cheaper path by construction) and every start()
    rides the device NBC tier (dev_persistent_starts)."""
    _reload(MV2T_DAEMON="1", MV2T_DAEMON_DIR=ddir,
            MV2T_DAEMON_EXEC_CACHE="1", MV2T_DEVICE_COLL_MIN_BYTES="1")
    p0 = mpit.pvar("dev_persistent_starts").read()

    def app(comm):
        x = np.arange(256, dtype=np.float32) + comm.rank
        out = np.zeros_like(x)
        req = comm.allreduce_init(x, out)
        for _ in range(3):
            req.start()
            req.wait()
            np.testing.assert_array_equal(
                out, (np.arange(256, dtype=np.float32) * comm.size
                      + sum(range(comm.size))))
        req.free()

    m0 = mpit.pvar("exec_cache_misses").read()
    run_ranks(2, app, device_mesh=True)          # cold: builds + caches
    starts_cold = mpit.pvar("dev_persistent_starts").read()
    assert starts_cold >= p0 + 2 * 3, "starts did not ride device NBC"
    assert mpit.pvar("exec_cache_misses").read() > m0
    h0 = mpit.pvar("exec_cache_hits").read()
    run_ranks(2, app, device_mesh=True)          # warm: deserialize
    assert mpit.pvar("exec_cache_hits").read() > h0
    assert mpit.pvar("dev_persistent_starts").read() >= starts_cold + 6


def test_persistent_alltoallv_starts():
    """alltoallv_init: the counts matrix is cross-rank state so init
    cannot pre-build, but every start() still routes device NBC."""
    _reload(MV2T_DEVICE_COLL_MIN_BYTES="1")
    p0 = mpit.pvar("dev_persistent_starts").read()

    def app(comm):
        p, r = comm.size, comm.rank
        counts = _count_matrix(p, "skew")
        send, scounts, rcounts, expect = _v_bufs(p, r, counts, np.int32)
        recv = np.zeros(max(1, sum(rcounts)), np.int32)
        req = comm.alltoallv_init(send, scounts, None, recv, rcounts,
                                  None)
        for _ in range(2):
            recv[:] = 0
            req.start()
            req.wait()
            np.testing.assert_array_equal(recv[:sum(rcounts)], expect)
        req.free()

    run_ranks(4, app, device_mesh=True)
    assert mpit.pvar("dev_persistent_starts").read() >= p0 + 4 * 2


# ---------------------------------------------------------------------------
# chaos: rank death mid-flight unwinds survivor DAGs, no leaked state
# ---------------------------------------------------------------------------

def _chaos_mid_icoll(nr, victim, coll):
    outcome = {}

    def app(comm):
        p, r = comm.size, comm.rank
        if r == victim:
            time.sleep(0.5)     # survivors deposit + park in wait first
            raise RuntimeError("chaos: victim dies mid i-collective")
        if coll == "iallreduce":
            x = np.ones(64, np.int32)
            req = comm.iallreduce(x, np.zeros_like(x))
        elif coll == "ialltoallv":
            counts = _count_matrix(p, "skew")
            send, sc, rc, _ = _v_bufs(p, r, counts, np.int32)
            req = comm.ialltoallv(send, sc, None,
                                  np.zeros(max(1, sum(rc)), np.int32),
                                  rc, None)
        else:
            send = np.zeros(p * 4, np.int32)
            req = comm.ialltoall(send, np.zeros_like(send))
        assert getattr(req, "device_nbc", False)
        try:
            req.wait()
            outcome[r] = "completed"
        except MPIException as e:
            outcome[r] = e.error_class

    with pytest.raises(RuntimeError):
        run_ranks(nr, app, device_mesh=True, timeout=60)
    assert outcome and all(v == MPIX_ERR_PROC_FAILED
                           for v in outcome.values()), outcome
    assert mpit.pvar("nbc_scheds_active").read() == 0, \
        "leaked parked NBC schedule after unwind"


def test_rank_death_mid_ialltoall_unwinds():
    """Tier-1 seeded chaos case: victim dies while survivors are parked
    in wait() on a device ialltoall — every survivor unwinds with
    MPIX_ERR_PROC_FAILED and no schedule leaks."""
    _reload(MV2T_DEVICE_COLL_MIN_BYTES="1")
    _chaos_mid_icoll(4, 1, "ialltoall")


def test_rank_death_mid_persistent_start_unwinds():
    """Tier-1 seeded: a completed persistent round, then the victim
    dies before the next start — survivors' start()+wait() unwinds with
    MPIX_ERR_PROC_FAILED; no leaked schedules."""
    _reload(MV2T_DEVICE_COLL_MIN_BYTES="1")
    outcome = {}

    def app(comm):
        p, r = comm.size, comm.rank
        x = np.arange(32, dtype=np.float32) + r
        out = np.zeros_like(x)
        req = comm.allreduce_init(x, out)
        req.start()
        req.wait()                      # round 1: everyone alive
        np.testing.assert_array_equal(
            out, np.arange(32, dtype=np.float32) * p + sum(range(p)))
        if r == 2:
            time.sleep(0.5)
            raise RuntimeError("chaos: victim dies before restart")
        try:
            req.start()
            req.wait()
            outcome[r] = "completed"
        except MPIException as e:
            outcome[r] = e.error_class

    with pytest.raises(RuntimeError):
        run_ranks(4, app, device_mesh=True, timeout=60)
    assert outcome and all(v == MPIX_ERR_PROC_FAILED
                           for v in outcome.values()), outcome
    assert mpit.pvar("nbc_scheds_active").read() == 0


@pytest.mark.chaos
@pytest.mark.parametrize("coll", ["iallreduce", "ialltoall",
                                  "ialltoallv"])
@pytest.mark.parametrize("victim", [0, 1, 3])
def test_chaos_matrix_mid_icoll(coll, victim):
    """Full victim x op matrix (runtests --chaos lane)."""
    _reload(MV2T_DEVICE_COLL_MIN_BYTES="1")
    _chaos_mid_icoll(4, victim, coll)


# ---------------------------------------------------------------------------
# observability: gated lat_dev_nbc histogram + trace instants
# ---------------------------------------------------------------------------

def _nbc_app_with_tracecap(seen):
    def app(comm):
        x = np.arange(256, dtype=np.int32) + comm.rank
        out = np.zeros_like(x)
        req = comm.iallreduce(x, out)
        assert getattr(req, "device_nbc", False)
        req.wait()
        if comm.rank == 0:
            tr = comm.u.engine.tracer
            if tr is not None:
                seen["names"] = {e[2] for e in tr.tail(100000)
                                 if e[1] == "device"}
    return app


def test_nbc_device_observability(monkeypatch):
    """MV2T_METRICS=1 records the lat_dev_nbc histogram per completed
    segment; the device trace lane carries nbc_dev_issue/complete
    instants."""
    monkeypatch.setenv("MV2T_TRACE", "1")
    _reload(MV2T_DEVICE_COLL_MIN_BYTES="1",
            MV2T_DEVICE_NBC_SEG_BYTES="256", MV2T_METRICS="1")
    h = mpit.pvar("lat_dev_nbc")
    c0 = h.count
    seen = {}
    run_ranks(2, _nbc_app_with_tracecap(seen), device_mesh=True)
    assert h.count > c0, "lat_dev_nbc histogram did not record"
    assert {"nbc_dev_issue", "nbc_dev_complete"} <= seen.get(
        "names", set()), seen


def test_nbc_histogram_gated_off():
    """MV2T_METRICS=0: the telemetry gate stays disarmed and the
    lat_dev_nbc histogram records nothing."""
    from mvapich2_tpu import metrics as metrics_mod
    _reload(MV2T_DEVICE_COLL_MIN_BYTES="1", MV2T_METRICS="0")
    live_prev, metrics_mod.LIVE = metrics_mod.LIVE, None
    h = mpit.pvar("lat_dev_nbc")
    c0 = h.count
    try:
        def app(comm):
            x = np.ones(256, np.int32)
            out = np.zeros_like(x)
            req = comm.iallreduce(x, out)
            req.wait()

        run_ranks(2, app, device_mesh=True)
        assert h.count == c0, "histogram recorded under MV2T_METRICS=0"
    finally:
        metrics_mod.LIVE = live_prev

"""Device-resident RMA windows on the 8-device virtual CPU mesh —
HBM windows with epoch-compiled one-sided ops (rma/device.py; the
direct-RDMA analog of gen2/rdma_iba_1sc.c)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from mvapich2_tpu.parallel import MeshComm, make_mesh  # noqa: E402
from mvapich2_tpu.rma.device import DeviceWin, pallas_put  # noqa: E402


@pytest.fixture(scope="module")
def comm8():
    return MeshComm(make_mesh((8,), ("x",)))


def test_put_fence(comm8):
    win = DeviceWin(comm8, 16)
    for o in range(8):
        win.put(np.full(4, 10.0 + o), origin=o, target=(o + 1) % 8,
                disp=2)
    win.fence()
    for t in range(8):
        left = (t - 1) % 8
        row = win.local(t)
        np.testing.assert_allclose(row[2:6], np.full(4, 10.0 + left))
        np.testing.assert_allclose(row[:2], 0.0)
        np.testing.assert_allclose(row[6:], 0.0)


def test_get_fence(comm8):
    win = DeviceWin(comm8, 8)
    for r in range(8):
        win.store(r, 0, np.arange(8, dtype=np.float32) + 100 * r)
    h = win.get(3, origin=2, target=5, disp=4)
    win.fence()
    np.testing.assert_allclose(h.value(),
                               np.arange(4, 7, dtype=np.float32) + 500)


def test_accumulate_and_epoch_reuse(comm8):
    win = DeviceWin(comm8, 4)
    # every rank accumulates into rank 0 — ops apply in order
    for o in range(8):
        win.accumulate(np.full(4, float(o + 1)), origin=o, target=0)
    win.fence()
    np.testing.assert_allclose(win.local(0), np.full(4, 36.0))
    # identical second epoch reuses the cached compiled program
    assert len(win._epoch_cache) == 1
    for o in range(8):
        win.accumulate(np.full(4, float(o + 1)), origin=o, target=0)
    win.fence()
    assert len(win._epoch_cache) == 1
    np.testing.assert_allclose(win.local(0), np.full(4, 72.0))


def test_mixed_epoch_put_then_get(comm8):
    win = DeviceWin(comm8, 8)
    win.put(np.array([7.0, 8.0]), origin=3, target=6, disp=1)
    h = win.get(2, origin=0, target=6, disp=1)   # sees the put (ordered)
    win.fence()
    np.testing.assert_allclose(h.value(), [7.0, 8.0])


def test_devicewin_rdma_tier_dispatch_and_pvars(comm8):
    """A contiguous put on an interpret-mode window takes the
    remote-DMA tier — visible in the dev_rma_tier_rdma pvar — and
    lands only on the target shard."""
    from mvapich2_tpu import mpit
    before = mpit.pvar("dev_rma_tier_rdma").read()
    win = DeviceWin(comm8, 16, interpret=True)
    win.put(np.arange(4, dtype=np.float32) + 1.0, origin=2, target=5,
            disp=3)
    win.fence()
    np.testing.assert_allclose(win.local(5)[3:7], [1.0, 2.0, 3.0, 4.0])
    for r in range(8):
        if r != 5:
            np.testing.assert_allclose(win.local(r), 0.0)
    assert mpit.pvar("dev_rma_tier_rdma").read() - before >= 1


def test_devicewin_lock_flush_unlock(comm8):
    """Passive-target grammar end-to-end: lock opens the epoch, flush
    completes queued ops on the locked rank (the get handle resolves),
    unlock closes with a final flush."""
    from mvapich2_tpu import mpit
    win = DeviceWin(comm8, 16, interpret=True)
    win.store(6, 0, np.arange(16, dtype=np.float32))
    before = mpit.pvar("dev_rma_flush").read()
    win.lock(6)
    h = win.get(5, origin=1, target=6, disp=2)
    win.flush(6)
    np.testing.assert_allclose(h.value(), np.arange(2, 7,
                                                    dtype=np.float32))
    win.accumulate(np.full(3, 2.5, np.float32), origin=0, target=6,
                   disp=1)
    win.unlock(6)
    np.testing.assert_allclose(win.local(6)[1:4],
                               np.arange(1, 4, dtype=np.float32) + 2.5)
    assert mpit.pvar("dev_rma_flush").read() - before >= 2
    # grammar violations raise
    win.lock(3)
    with pytest.raises(RuntimeError):
        win.lock(3)
    win.unlock(3)
    with pytest.raises(RuntimeError):
        win.unlock(3)


def test_devicewin_flush_is_per_target(comm8):
    """flush(rank) completes only that target's queued ops; the rest
    stay pending until the epoch closes."""
    win = DeviceWin(comm8, 8, interpret=True)
    win.put(np.full(2, 3.0, np.float32), origin=0, target=3, disp=0)
    win.put(np.full(2, 4.0, np.float32), origin=0, target=6, disp=0)
    win.flush(3)
    np.testing.assert_allclose(win.local(3)[:2], 3.0)
    np.testing.assert_allclose(win.local(6)[:2], 0.0)   # still queued
    assert len(win._queue) == 1
    win.fence()
    np.testing.assert_allclose(win.local(6)[:2], 4.0)


def test_devicewin_strided_put_epoch_fallback(comm8):
    """A strided (non-contiguous) op falls back to the epoch compiler
    — counted in dev_rma_fallback_noncontig — with scatter
    semantics."""
    from mvapich2_tpu import mpit
    before = mpit.pvar("dev_rma_fallback_noncontig").read()
    win = DeviceWin(comm8, 16, dtype=jnp.int32, interpret=True)
    win.put(np.arange(4, dtype=np.int32) + 7, origin=0, target=2,
            disp=1, stride=3)
    win.fence()
    row = np.asarray(win.local(2))
    assert list(row[[1, 4, 7, 10]]) == [7, 8, 9, 10], row
    assert mpit.pvar("dev_rma_fallback_noncontig").read() - before >= 1


def test_devicewin_int32_rdma_epoch_bit_agreement(comm8):
    """Integer-valued data through the remote-DMA tier agrees bit-for-
    bit with the epoch-compiler lowering of the same op sequence."""
    a = DeviceWin(comm8, 8, dtype=jnp.int32, interpret=True)   # rdma
    b = DeviceWin(comm8, 8, dtype=jnp.int32)                   # epoch
    for w in (a, b):
        w.put(np.arange(5, dtype=np.int32) * 3 + 1, origin=3, target=7,
              disp=2)
        w.accumulate(np.full(5, 11, np.int32), origin=4, target=7,
                     disp=2)
        w.fence()
    assert np.array_equal(np.asarray(a.local(7)), np.asarray(b.local(7)))
    # the two windows really took different tiers
    assert a._op_tier(("put", 3, 7, 2, 5, 1))[0] == "rdma"
    assert b._op_tier(("put", 3, 7, 2, 5, 1))[0] == "epoch"


def test_pallas_put_interpret(comm8):
    """The explicit remote-DMA put kernel (interpret mode on the CPU
    mesh; on hardware the same kernel is an ICI remote DMA)."""
    mesh = comm8.mesh
    from mvapich2_tpu.parallel.mesh import shard_map

    win = jax.device_put(
        jnp.zeros((8, 8), jnp.float32),
        jax.sharding.NamedSharding(mesh, P("x")))
    src = jnp.arange(4, dtype=jnp.float32) + 1.0

    def prog(w_row):
        out = pallas_put(src, w_row[0], "x", origin=2, target=5, disp=3,
                         interpret=True)
        return out[None, :]

    f = shard_map(prog, mesh=mesh, in_specs=(P("x"),),
                  out_specs=P("x"), check_vma=False)
    out = np.asarray(jax.jit(f)(win))
    np.testing.assert_allclose(out[5, 3:7], [1.0, 2.0, 3.0, 4.0])
    np.testing.assert_allclose(out[5, :3], 0.0)
    for r in range(8):
        if r != 5:
            np.testing.assert_allclose(out[r], 0.0)

"""RMA (one-sided) tests — modeled on the reference's test/mpi/rma area
(putfence, getfence, accfence, lockcontention, fetchandadd, compare_and_swap,
pscw — each prints "No Errors" on success there; here they are asserts)."""

import numpy as np
import pytest

from mvapich2_tpu import mpi
from mvapich2_tpu.core import op as opmod
from mvapich2_tpu.rma.win import LOCK_EXCLUSIVE, LOCK_SHARED
from mvapich2_tpu.runtime.universe import run_ranks


N = 4


def test_put_fence():
    def body(comm):
        size = comm.size
        buf = np.full(8, comm.rank, dtype=np.int64)
        win = comm.win_create(buf, disp_unit=8)
        win.fence()
        # everyone puts its rank into the right neighbor's slot 0..7
        right = (comm.rank + 1) % size
        src = np.full(8, comm.rank + 100, dtype=np.int64)
        win.put(src, right, 0)
        win.fence()
        left = (comm.rank - 1) % size
        assert np.all(buf == left + 100), buf
        win.free()
    run_ranks(N, body)


def test_get_fence():
    def body(comm):
        buf = np.arange(16, dtype=np.float64) * (comm.rank + 1)
        win = comm.win_create(buf, disp_unit=8)
        win.fence()
        out = np.zeros(16, dtype=np.float64)
        target = (comm.rank + 1) % comm.size
        win.get(out, target, 0)
        win.fence()
        assert np.allclose(out, np.arange(16) * (target + 1))
        win.free()
    run_ranks(N, body)


def test_accumulate_sum_fence():
    def body(comm):
        buf = np.zeros(4, dtype=np.int64)
        win = comm.win_create(buf, disp_unit=8)
        win.fence()
        # all ranks accumulate into rank 0
        contrib = np.full(4, comm.rank + 1, dtype=np.int64)
        win.accumulate(contrib, 0, 0, op=opmod.SUM)
        win.fence()
        if comm.rank == 0:
            expect = sum(r + 1 for r in range(comm.size))
            assert np.all(buf == expect), buf
        win.free()
    run_ranks(N, body)


def test_accumulate_replace_and_disp():
    def body(comm):
        buf = np.zeros(8, dtype=np.int32)
        win = comm.win_create(buf, disp_unit=4)
        win.fence()
        # each rank replaces its own slot in every peer's window
        val = np.array([comm.rank + 7], dtype=np.int32)
        for t in range(comm.size):
            win.accumulate(val, t, comm.rank, op=opmod.REPLACE)
        win.fence()
        for r in range(comm.size):
            assert buf[r] == r + 7, buf
        win.free()
    run_ranks(N, body)


def test_get_accumulate_and_fetch_op():
    def body(comm):
        buf = np.zeros(1, dtype=np.int64)
        win = comm.win_create(buf, disp_unit=8)
        win.lock(0, LOCK_EXCLUSIVE)
        one = np.array([1], dtype=np.int64)
        old = np.zeros(1, dtype=np.int64)
        win.fetch_and_op(one, old, 0, 0, op=opmod.SUM)
        win.unlock(0)
        comm.barrier()
        if comm.rank == 0:
            assert buf[0] == comm.size   # every rank added exactly 1
        # the fetched "old" values must be a permutation of 0..size-1
        allold = np.zeros(comm.size, dtype=np.int64)
        comm.allgather(old, allold, count=1)
        assert sorted(allold.tolist()) == list(range(comm.size))
        win.free()
    run_ranks(N, body)


def test_compare_and_swap():
    def body(comm):
        buf = np.zeros(1, dtype=np.int64)
        win = comm.win_create(buf, disp_unit=8)
        win.lock_all()
        # everyone tries to CAS 0 -> rank+1 at rank 0; exactly one wins
        mine = np.array([comm.rank + 1], dtype=np.int64)
        comp = np.array([0], dtype=np.int64)
        result = np.array([-1], dtype=np.int64)
        win.compare_and_swap(mine, comp, result, 0, 0)
        win.unlock_all()
        comm.barrier()
        wins = np.zeros(comm.size, dtype=np.int64)
        got = np.array([1 if result[0] == 0 else 0], dtype=np.int64)
        comm.allgather(got, wins, count=1)
        assert wins.sum() == 1, wins          # exactly one CAS succeeded
        if comm.rank == 0:
            assert buf[0] in range(1, comm.size + 1)
        win.free()
    run_ranks(N, body)


def test_lock_exclusive_counter():
    """Contended exclusive-lock increments (lockcontention analog)."""
    def body(comm):
        buf = np.zeros(1, dtype=np.int64)
        win = comm.win_create(buf, disp_unit=8)
        for _ in range(5):
            win.lock(0, LOCK_EXCLUSIVE)
            cur = np.zeros(1, dtype=np.int64)
            win.get(cur, 0, 0)
            win.flush(0)
            cur += 1
            win.put(cur, 0, 0)
            win.unlock(0)
        comm.barrier()
        if comm.rank == 0:
            assert buf[0] == 5 * comm.size, buf
        win.free()
    run_ranks(N, body)


def test_pscw():
    """post/start/complete/wait generic active target (pscw analog)."""
    def body(comm):
        size = comm.size
        buf = np.zeros(4, dtype=np.int64)
        win = comm.win_create(buf, disp_unit=8)
        even = comm.rank % 2 == 0
        peer = comm.rank + 1 if even else comm.rank - 1
        if peer >= size:
            win.free()
            return
        peer_group = comm.group.incl([peer])
        if even:
            # origin: start/put/complete
            win.start(peer_group)
            win.put(np.full(4, comm.rank + 50, dtype=np.int64), peer, 0)
            win.complete()
        else:
            win.post(peer_group)
            win.wait()
            assert np.all(buf == peer + 50), buf
        win.free()
    run_ranks(N, body)


def test_win_allocate_and_flush():
    def body(comm):
        win = comm.win_allocate(64, disp_unit=8)
        win.lock_all()
        v = np.array([comm.rank * 11], dtype=np.int64)
        win.put(v, (comm.rank + 1) % comm.size, 2)
        win.flush_all()
        win.unlock_all()
        comm.barrier()
        left = (comm.rank - 1) % comm.size
        local = win.base.view(np.int64)
        assert local[2] == left * 11
        win.free()
    run_ranks(N, body)


def test_dynamic_window():
    def body(comm):
        win = comm.win_create_dynamic()
        arr = np.zeros(8, dtype=np.float32)
        addr = win.attach(arr)
        addrs = np.zeros(comm.size, dtype=np.int64)
        comm.allgather(np.array([addr], dtype=np.int64), addrs, count=1)
        win.fence()
        t = (comm.rank + 1) % comm.size
        win.put(np.full(8, 2.5 * (comm.rank + 1), dtype=np.float32),
                t, int(addrs[t]))
        win.fence()
        left = (comm.rank - 1) % comm.size
        assert np.allclose(arr, 2.5 * (left + 1))
        win.detach(addr)
        win.free()
    run_ranks(N, body)


def test_derived_datatype_put():
    """Put with a vector target datatype (non-contiguous scatter)."""
    from mvapich2_tpu.core import datatype as dt
    def body(comm):
        buf = np.zeros(16, dtype=np.int32)
        win = comm.win_create(buf, disp_unit=1)
        win.fence()
        if comm.rank == 0:
            # every 2nd int in ranks' windows
            vec = dt.create_vector(4, 1, 2, dt.INT).commit()
            src = np.arange(4, dtype=np.int32) + 1
            for t in range(comm.size):
                win.put(src, t, 0, count=1,
                        origin_dt=dt.create_contiguous(4, dt.INT).commit(),
                        target_dt=vec)
        win.fence()
        assert np.all(buf[0:8:2] == np.arange(4) + 1), buf
        assert np.all(buf[1:8:2] == 0)
        win.free()
    run_ranks(N, body)


def test_rget_rput_requests():
    def body(comm):
        buf = np.full(4, comm.rank, dtype=np.int64)
        win = comm.win_create(buf, disp_unit=8)
        win.lock_all()
        t = (comm.rank + 1) % comm.size
        out = np.zeros(4, dtype=np.int64)
        req = win.rget(out, t, 0)
        req.wait()
        assert np.all(out == t)
        win.unlock_all()
        win.free()
    run_ranks(N, body)


def test_shared_window():
    def body(comm):
        win = comm.win_allocate_shared(32, disp_unit=8)
        mine = win.base.view(np.int64)
        mine[:] = comm.rank + 1
        comm.barrier()
        # direct load/store into a peer's segment
        peer = (comm.rank + 1) % comm.size
        pbuf, psize, punit = win.shared_query(peer)
        assert psize == 32 and punit == 8
        assert np.all(pbuf.view(np.int64) == peer + 1)
        comm.barrier()
        win.free()
    run_ranks(N, body)


def test_rma_sync_errors():
    def body(comm):
        buf = np.zeros(2, dtype=np.int64)
        win = comm.win_create(buf, disp_unit=8)
        from mvapich2_tpu.core.errors import MPIException
        with pytest.raises(MPIException):
            win.put(np.array([1], dtype=np.int64), 0, 0)  # no epoch
        win.fence()
        win.free()
    run_ranks(2, body)


def test_self_rma():
    """COMM_SELF-style loopback window ops."""
    def body(comm):
        buf = np.zeros(4, dtype=np.int64)
        win = comm.win_create(buf, disp_unit=8)
        win.fence()
        win.put(np.arange(4, dtype=np.int64), comm.rank, 0)
        win.fence()
        assert np.all(buf == np.arange(4))
        out = np.zeros(4, dtype=np.int64)
        win.get(out, comm.rank, 0)
        win.fence()
        assert np.all(out == np.arange(4))
        win.free()
    run_ranks(2, body)

"""Shared-memory ring unit tests (native C++ + Python fallback parity)."""

import os
import tempfile
import uuid

import numpy as np
import pytest

from mvapich2_tpu.transport import shm as shm_mod


def _mk(ring_cls_native: bool, nranks=2, ring_bytes=4096):
    path = os.path.join("/dev/shm" if os.path.isdir("/dev/shm")
                        else tempfile.gettempdir(),
                        f"mv2t-test-{uuid.uuid4().hex[:8]}")
    if ring_cls_native:
        lib = shm_mod._load_native()
        if lib is None:
            pytest.skip("native shmring unavailable")
        ring = shm_mod._NativeRing(lib, path, nranks, ring_bytes, True)
    else:
        ring = shm_mod._PyRing(path, nranks, ring_bytes, True)
    return ring, path


@pytest.mark.parametrize("native", [True, False])
def test_ring_roundtrip(native):
    ring, path = _mk(native)
    try:
        assert ring.send(0, 1, b"hello") == 1
        assert ring.send(0, 1, b"world!") == 1
        assert ring.recv(0, 1) == b"hello"
        assert ring.recv(0, 1) == b"world!"
        assert ring.recv(0, 1) is None
    finally:
        ring.close(); os.unlink(path)


@pytest.mark.parametrize("native", [True, False])
def test_ring_wrap_and_full(native):
    ring, path = _mk(native, ring_bytes=1024)
    try:
        msg = b"x" * 100
        sent = 0
        while ring.send(0, 1, msg) == 1:
            sent += 1
        assert sent >= 6              # filled up
        for _ in range(sent):
            assert ring.recv(0, 1) == msg
        # wrap: keep cycling through the boundary repeatedly
        for i in range(100):
            payload = bytes([i % 250]) * (50 + i % 60)
            assert ring.send(1, 0, payload) == 1
            assert ring.recv(1, 0) == payload
    finally:
        ring.close(); os.unlink(path)


@pytest.mark.parametrize("native", [True, False])
def test_ring_oversize_rejected(native):
    ring, path = _mk(native, ring_bytes=1024)
    try:
        assert ring.send(0, 1, b"y" * 2000) == -1
    finally:
        ring.close(); os.unlink(path)


def test_native_python_layout_parity():
    """Python fallback can read what C++ wrote (same layout)."""
    lib = shm_mod._load_native()
    if lib is None:
        pytest.skip("native shmring unavailable")
    path = os.path.join("/dev/shm" if os.path.isdir("/dev/shm")
                        else tempfile.gettempdir(),
                        f"mv2t-parity-{uuid.uuid4().hex[:8]}")
    nat = shm_mod._NativeRing(lib, path, 2, 4096, True)
    py = shm_mod._PyRing(path, 2, 4096, False)
    try:
        assert nat.send(0, 1, b"from-native") == 1
        assert py.recv(0, 1) == b"from-native"
        assert py.send(1, 0, b"from-python") == 1
        # native reader
        got = nat.recv(1, 0)
        assert got == b"from-python"
    finally:
        nat.close(); py.close(); os.unlink(path)

"""Regression tests: non-commutative user ops must fold in rank order on
EVERY reduction path (blocking, nonblocking, scan, reduce_scatter), and
unsupported negative datatype displacements must be rejected loudly."""

import functools

import numpy as np
import pytest

from mvapich2_tpu import run_ranks
from mvapich2_tpu.core import datatype as dt
from mvapich2_tpu.core import op as opmod
from mvapich2_tpu.core.errors import MPIException


def _matmul_op():
    # 2x2 matrix multiply flattened into 4 doubles — order-sensitive
    def f(invec, inout):
        a = invec.reshape(-1, 2, 2)
        b = inout.reshape(-1, 2, 2)
        return np.matmul(a, b).reshape(invec.shape)
    return opmod.create_op(f, commute=False)


def _mat(rank, nblk=1):
    m = np.array([[1.0, rank + 1], [0.0, 1.0]])
    return np.tile(m.reshape(-1), nblk)


def _expected_prefix(upto, nblk=1):
    acc = np.eye(2)
    for r in range(upto + 1):
        acc = acc @ np.array([[1.0, r + 1], [0.0, 1.0]])
    return np.tile(acc.reshape(-1), nblk)


@pytest.mark.parametrize("nranks", [3, 4])
def test_allreduce_noncommutative(nranks):
    def fn(comm):
        out = comm.allreduce(_mat(comm.rank), op=_matmul_op())
        np.testing.assert_allclose(out, _expected_prefix(comm.size - 1))
    run_ranks(nranks, fn)


@pytest.mark.parametrize("nranks", [3, 4])
def test_iallreduce_noncommutative(nranks):
    def fn(comm):
        rb = np.zeros(4)
        comm.iallreduce(_mat(comm.rank), rb, op=_matmul_op()).wait()
        np.testing.assert_allclose(rb, _expected_prefix(comm.size - 1))
    run_ranks(nranks, fn)


@pytest.mark.parametrize("nranks", [3, 4])
def test_scan_noncommutative(nranks):
    def fn(comm):
        out = comm.scan(_mat(comm.rank), op=_matmul_op())
        np.testing.assert_allclose(out, _expected_prefix(comm.rank))
    run_ranks(nranks, fn)


@pytest.mark.parametrize("nranks", [3, 4])
def test_exscan_noncommutative(nranks):
    def fn(comm):
        out = comm.exscan(_mat(comm.rank), op=_matmul_op())
        if comm.rank > 0:
            np.testing.assert_allclose(out, _expected_prefix(comm.rank - 1))
    run_ranks(nranks, fn)


@pytest.mark.parametrize("nranks", [4])
def test_reduce_scatter_block_noncommutative(nranks):
    def fn(comm):
        sb = _mat(comm.rank, nblk=comm.size)
        rb = comm.reduce_scatter_block(sb, op=_matmul_op(), count=4)
        np.testing.assert_allclose(rb, _expected_prefix(comm.size - 1))
    run_ranks(nranks, fn)


def test_reduce_noncommutative_nonroot_order():
    def fn(comm):
        out = comm.reduce(_mat(comm.rank), op=_matmul_op(), root=2)
        if comm.rank == 2:
            np.testing.assert_allclose(out, _expected_prefix(comm.size - 1))
    run_ranks(4, fn)


def test_negative_stride_bounds_and_pack_guard():
    # negative strides/displacements are legal MPI (datatype/lbub.c);
    # bounds follow the MPI-1 §3.12.3 min/max rule and the pointer-view
    # pack refuses (abs ctypes path required) instead of wrap-indexing
    v = dt.create_vector(2, 1, -1, dt.INT)
    assert v.lb == -4 and v.extent == 8 and v.size == 8
    h = dt.create_hindexed([1, 1], [0, -8], dt.DOUBLE)
    assert h.lb == -8 and h.extent == 16 and h.size == 16
    buf = np.zeros(4, np.int32)
    with pytest.raises(MPIException):
        v.pack(buf, 1)
    with pytest.raises(MPIException):
        h.unpack(np.zeros(16, np.uint8), buf, 1)


def test_sticky_lb_ub_replication():
    # resized(lb=-3, extent=9) over 4 bytes of data: vector(3,1,1)
    # must report lb=-3, ub=24, extent=27 (datatype/lbub.c expectations)
    base = dt.create_resized(dt.create_contiguous(4, dt.BYTE), -3, 9)
    v = dt.create_vector(3, 1, 1, base)
    assert (v.lb, v.ub, v.extent, v.size) == (-3, 24, 27, 12)
    c = dt.create_contiguous(3, base)
    assert (c.lb, c.ub, c.extent) == (-3, 24, 27)
    # negative extent tiles backward: contig(3) of resized(lb=6, ext=-9)
    neg = dt.create_resized(dt.create_contiguous(4, dt.BYTE), 6, -9)
    cn = dt.create_contiguous(3, neg)
    assert (cn.lb, cn.ub, cn.extent) == (-12, -3, 9)

"""Runtime lock-order detector tests: off-mode identity (zero
overhead), cycle detection with exactly-one-report semantics through
the watchdog dump path (tests/progs/lockcheck_cycle_prog.py), the
held-across-progress-wait check, and the lockcheck-off overhead guard
mirroring trace_overhead_prog.py."""

import os
import subprocess
import sys
import threading

import pytest

from mvapich2_tpu.analysis import lockorder
from mvapich2_tpu.utils.config import get_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.lint


@pytest.fixture
def monitor():
    """Force the monitor on for one test, restoring the off state (and
    the cached singleton) afterwards so the rest of the suite keeps the
    zero-overhead raw locks."""
    import mvapich2_tpu.mpit  # noqa: F401  (declares the LOCKCHECK cvar)
    get_config().set("LOCKCHECK", True)
    old = lockorder._monitor
    lockorder._monitor = None
    try:
        yield lockorder.get_monitor()
    finally:
        lockorder._monitor = old
        get_config().set("LOCKCHECK", False)


def test_tracked_is_identity_when_off():
    import mvapich2_tpu.mpit  # noqa: F401
    get_config().set("LOCKCHECK", False)
    raw = threading.Lock()
    assert lockorder.tracked(raw, "probe") is raw


def test_cycle_detected_once_with_both_sites(monitor):
    a = lockorder.tracked(threading.Lock(), "t.A")
    b = lockorder.tracked(threading.Lock(), "t.B")
    assert isinstance(a, lockorder.TrackedLock)

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    for fn in (ab, ba, ab, ba):
        t = threading.Thread(target=fn)
        t.start()
        t.join()
    assert len(monitor.cycle_reports) == 1
    rep = monitor.cycle_reports[0]
    assert "t.A" in rep and "t.B" in rep
    assert rep.count("test_lockcheck.py:") >= 2   # both sites named
    assert "potential deadlock" in rep


def test_three_lock_cycle(monitor):
    locks = [lockorder.tracked(threading.Lock(), f"t3.L{i}")
             for i in range(3)]

    def chain(i, j):
        with locks[i]:
            with locks[j]:
                pass

    for i, j in [(0, 1), (1, 2), (2, 0)]:
        t = threading.Thread(target=chain, args=(i, j))
        t.start()
        t.join()
    assert len(monitor.cycle_reports) == 1
    assert all(f"t3.L{i}" in monitor.cycle_reports[0] for i in range(3))


def test_reentrant_rlock_no_self_cycle(monitor):
    r = lockorder.tracked(threading.RLock(), "t.R")
    with r:
        with r:
            pass
    assert monitor.cycle_reports == []


def test_failed_try_acquire_records_nothing(monitor):
    a = lockorder.tracked(threading.Lock(), "t.FA")
    b = lockorder.tracked(threading.Lock(), "t.FB")
    b._lock.acquire()        # someone else holds b
    try:
        with a:
            assert b.acquire(blocking=False) is False
    finally:
        b._lock.release()
    assert ("t.FA", "t.FB") not in monitor._edges


def test_check_wait_reports_held_locks_once(monitor):
    a = lockorder.tracked(threading.Lock(), "t.W")
    with a:
        monitor.check_wait(0)
        monitor.check_wait(0)     # one-shot per thread
    assert len(monitor.wait_reports) == 1
    assert "t.W" in monitor.wait_reports[0]
    assert "progress_wait" in monitor.wait_reports[0]


def test_watchdog_report_carries_lockorder_section(monitor):
    from mvapich2_tpu.trace import watchdog

    class _Eng:
        rank = 0
        mutex = threading.RLock()
        outstanding = {}
        universe = None
        nbc = None
        tracer = None
        _lockcheck = monitor

    text = watchdog.build_report(_Eng())
    assert "lock-order monitor" in text


# -- end-to-end progs ----------------------------------------------------

def test_cycle_prog_exactly_one_report():
    """The deliberate 2-thread A->B / B->A prog: exactly one cycle
    report, both lock sites named, surfaced via the watchdog path."""
    prog = os.path.join(REPO, "tests", "progs", "lockcheck_cycle_prog.py")
    env = dict(os.environ, MV2T_LOCKCHECK="1", JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-m", "mvapich2_tpu.run", "-np",
                        "1", sys.executable, prog], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout
    assert r.stderr.count("potential deadlock cycle") == 1
    assert "lockcheck_cycle_prog.py:" in r.stderr


def test_lockcheck_off_overhead_guard():
    """Mirrors trace_overhead_prog.py: with MV2T_LOCKCHECK unset the
    engine locks are raw and the wait-path gate is one attribute check
    under 5% of message latency."""
    prog = os.path.join(REPO, "tests", "progs",
                        "lockcheck_overhead_prog.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("MV2T_LOCKCHECK", None)
    r = subprocess.run([sys.executable, "-m", "mvapich2_tpu.run", "-np",
                        "2", sys.executable, prog], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout


def test_lockcheck_on_real_workload_is_cycle_free():
    """A 2-rank thread-fabric collective + pt2pt workload under the
    monitor: edges are recorded, no cycles, no held-across-wait
    violations — the shipped lock discipline is clean at runtime."""
    import numpy as np
    prog_env = os.environ.get("MV2T_LOCKCHECK")
    import mvapich2_tpu.mpit as mpit
    get_config().set("LOCKCHECK", True)
    old = lockorder._monitor
    lockorder._monitor = None
    try:
        from mvapich2_tpu.runtime.universe import run_ranks

        def body(comm):
            comm.allreduce(np.ones(32))
            comm.sendrecv(np.ones(8), (comm.rank + 1) % comm.size, 1,
                          np.zeros(8), (comm.rank - 1) % comm.size, 1)
            comm.ibarrier().wait()
            return comm.u.engine._lockcheck is not None

        assert all(run_ranks(2, body))
        mon = lockorder.get_monitor()
        assert mon is not None
        assert len(mon._edges) > 0
        assert mon.cycle_reports == []
        assert mon.wait_reports == []
    finally:
        lockorder._monitor = old
        get_config().set("LOCKCHECK", False)
        if prog_env is None:
            os.environ.pop("MV2T_LOCKCHECK", None)

"""Run a fast slice of the UNMODIFIED MPICH conformance suite from the
reference tree against the C ABI (the reference's own oracle — SURVEY §4:
"the MPICH suite itself can be the conformance oracle"). The full curated
corpus runs via `bin/run_mpich_tests tests/progs/mpich_testlist`; this
pytest slice keeps a representative sample in CI.
"""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF = "/root/reference/test/mpi"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF) or shutil.which("gcc") is None,
    reason="reference MPICH suite or C toolchain unavailable")

# (area/prog, np) — one or two per area, chosen fast and representative
SLICE = [
    ("attr/attrt", 2),
    ("attr/fkeyval", 2),
    ("comm/dup", 2),
    ("comm/commname", 2),
    ("group/gtranks", 4),
    ("info/infotest", 1),
    ("errhan/adderr", 1),
    ("init/version", 1),
]


@pytest.fixture(scope="module")
def harness(tmp_path_factory):
    bld = str(tmp_path_factory.mktemp("mpich_slice"))
    sys.path.insert(0, os.path.join(REPO, "bin"))
    import importlib.util
    from importlib.machinery import SourceFileLoader
    # explicit loader: the runner has no .py suffix, and newer pythons
    # return a loaderless spec for unrecognized suffixes
    loader = SourceFileLoader(
        "run_mpich_tests", os.path.join(REPO, "bin", "run_mpich_tests"))
    spec = importlib.util.spec_from_loader("run_mpich_tests", loader)
    mod = importlib.util.module_from_spec(spec)
    loader.exec_module(mod)
    objs, incs = mod.build_harness(REF, bld, need_dtypes=False)
    return mod, bld, objs, incs


@pytest.mark.parametrize("spec,np_", SLICE,
                         ids=[s for s, _ in SLICE])
def test_mpich_program(harness, spec, np_):
    mod, bld, objs, incs = harness
    area, prog = spec.split("/", 1)
    exe, cerr = mod.compile_test(REF, bld, incs, objs, area, prog)
    assert exe is not None, f"compile failed:\n{cerr}"
    ok, detail = mod.run_test(exe, np_, [], timeout=240)
    assert ok, detail

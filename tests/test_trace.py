"""Distributed event tracing + stall watchdog tests (trace/).

Covers: ring-buffer recorder attach/dump over the thread harness,
Perfetto merge schema + event ordering (enter<=exit, vertex issue before
complete), the bin/mpitrace end-to-end flow on a 4-rank process-mode
allreduce+NBC workload, the one-shot stall watchdog, drain_all leftover
reporting, and the tracing-off overhead guard.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from mvapich2_tpu import mpit, trace
from mvapich2_tpu.runtime.universe import local_universe, run_ranks

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _workload(comm):
    comm.allreduce(np.full(64, float(comm.rank + 1)))
    big = np.full(1 << 17, float(comm.rank), np.float64)
    rbig = np.zeros(1 << 17, np.float64)
    comm.sendrecv(big, (comm.rank + 1) % comm.size, 3,
                  rbig, (comm.rank - 1) % comm.size, 3)
    rg = np.zeros(comm.size, np.float64)
    req = comm.iallgather(np.array([comm.rank * 2.0]), rg)
    req.wait()
    assert rg.tolist() == [r * 2.0 for r in range(comm.size)]
    return True


def _check_merged(merged, nranks):
    """Shared schema/ordering assertions for a merged trace."""
    evs = [e for e in merged["traceEvents"] if e["ph"] != "M"]
    assert {e["pid"] for e in evs} == set(range(nranks))
    layers = {e["cat"] for e in evs}
    assert {"mpi", "protocol", "progress", "nbc"} <= layers
    # B/E spans nest per (pid, cat, name): every E matches an open B at
    # an earlier-or-equal timestamp
    stacks = {}
    for e in evs:
        key = (e["pid"], e["cat"], e["name"])
        if e["ph"] == "B":
            stacks.setdefault(key, []).append(e["ts"])
        elif e["ph"] == "E":
            opens = stacks.get(key)
            assert opens, f"E without B: {key}"
            assert opens.pop() <= e["ts"]
    # nbc: per (pid, sched, vid) issue precedes complete
    marks = {}
    for e in evs:
        if e["cat"] != "nbc" or "args" not in e:
            continue
        a = e["args"]
        if e["name"] in ("vertex_issue", "vertex_complete"):
            key = (e["pid"], a["sched"], a["vid"])
            marks.setdefault(key, {})[e["name"]] = e["ts"]
    assert marks, "no nbc vertex events recorded"
    for key, m in marks.items():
        assert "vertex_issue" in m, f"complete without issue: {key}"
        if "vertex_complete" in m:
            assert m["vertex_issue"] <= m["vertex_complete"], key


def test_trace_inprocess_merge_schema_and_ordering(tmp_path, monkeypatch):
    """Thread-harness tracing: 4 ranks dump at finalize; the merged
    Perfetto JSON carries all ranks across >=4 layers with consistent
    event ordering."""
    monkeypatch.setenv("MV2T_TRACE", "1")
    monkeypatch.setenv("MV2T_TRACE_DIR", str(tmp_path))
    assert all(run_ranks(4, _workload))
    dumps = trace.read_dumps(str(tmp_path))
    assert [d["rank"] for d in dumps] == [0, 1, 2, 3]
    merged = trace.merge_dir(str(tmp_path),
                             str(tmp_path / "merged.json"))
    _check_merged(merged, 4)
    # the thread fabric routes through python send_packet, so the
    # channel lane is populated too (process mode may route around it
    # via the C plane's own counters — see README)
    assert "channel" in {e["cat"] for e in merged["traceEvents"]
                         if e["ph"] != "M"}
    assert json.load(open(tmp_path / "merged.json"))["traceEvents"]
    text = trace.summarize(dumps)
    assert "mpi" in text and "nbc" in text


def test_trace_off_is_detached():
    """Default (cvar off): no recorder attaches and the MPI method table
    stays unwrapped after a traced run ends."""
    from mvapich2_tpu import profile

    def body(comm):
        comm.barrier()
        return comm.u.engine.tracer is None

    assert all(run_ranks(2, body))
    assert not profile._installed


def test_trace_ring_buffer_bounded(monkeypatch):
    monkeypatch.setenv("MV2T_TRACE", "1")
    monkeypatch.setenv("MV2T_TRACE_BUF", "256")
    caps = []

    def body(comm):
        for _ in range(50):
            comm.allreduce(np.ones(4))
        caps.append(len(comm.u.engine.tracer.events))
        return True

    assert all(run_ranks(2, body))
    assert all(c <= 256 for c in caps)


def test_mpitrace_end_to_end(tmp_path):
    """Acceptance: bin/mpitrace -np 4 on an allreduce+iallgather+ireduce
    prog produces ONE merged Perfetto JSON with events from all 4 ranks
    across >=4 layers, plus the per-layer summary."""
    out = tmp_path / "merged.json"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "mpitrace"),
         "-np", "4", "--out", str(out), "--dir", str(tmp_path / "dumps"),
         sys.executable,
         os.path.join(REPO, "tests", "progs", "trace_workload_prog.py")],
        cwd=REPO, capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout
    assert "# trace summary" in r.stdout
    merged = json.load(open(out))
    _check_merged(merged, 4)
    # conformance stamp (ISSUE 19): a clean tier-1 run replays through
    # the protocol automata violation-free, on BOTH loader paths
    for target in (str(out), str(tmp_path / "dumps")):
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bin", "mv2tconform"),
             target], capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, f"{target}:\n{r.stdout}{r.stderr}"
        assert "0 violation(s)" in r.stdout


def test_stall_watchdog_trips_exactly_once(monkeypatch):
    """A receiver that never posts trips the watchdog ONCE, dumping the
    posted/unexpected queues, outstanding requests, and active NBC
    schedules — then the wait keeps going and completes normally."""
    monkeypatch.setenv("MV2T_STALL_TIMEOUT", "0.3")
    before = mpit.pvar("stall_watchdog_trips").read()
    reports = []

    def body(comm):
        if comm.rank == 0:
            nbc_req = comm.ibarrier()       # peer is asleep: stays active
            req = comm.irecv(np.zeros(4), source=1, tag=99)
            comm.u.engine.progress_wait(lambda: req.complete_flag,
                                        timeout=5.0)
            nbc_req.wait()
            reports.append(getattr(comm.u.engine, "_stall_report", ""))
            assert comm.u.engine._stall_tripped
        else:
            time.sleep(1.0)                 # force the stall window
            comm.send(np.ones(4), dest=0, tag=99)
            comm.ibarrier().wait()
        return True

    assert all(run_ranks(2, body))
    assert mpit.pvar("stall_watchdog_trips").read() - before == 1
    rep = reports[0]
    assert "stall watchdog" in rep
    assert "posted receives" in rep and "tag=99" in rep
    assert "unexpected messages" in rep
    assert "outstanding requests" in rep
    assert "active NBC schedules (1)" in rep


def test_stall_watchdog_off_by_default():
    def body(comm):
        assert comm.u.engine._stall_limit is None
        comm.barrier()
        return True

    assert all(run_ranks(2, body))


def test_drain_all_reports_leftover_work():
    """Satellite: drain_all returns how many packets/hook advances it
    retired so Finalize can log leftover traffic."""
    universes = local_universe(2)
    try:
        u0, u1 = universes
        from mvapich2_tpu.core import datatype as dt
        buf = np.ones(8, np.float64)
        u0.protocol.isend(buf, 8, dt.DOUBLE, dest_world=1, comm_src=0,
                          ctx=0, tag=5).wait()
        # the eager packet sits undispatched in rank 1's inbox
        assert u1.engine.drain_all() >= 1
        assert u1.engine.drain_all() == 0   # idempotent once quiet
    finally:
        for u in universes:
            u.finalize()


def test_trace_off_overhead_guard():
    """Satellite: tracing-off adds <5% to an osu_latency-shaped
    ping-pong in process mode (gate + counter unit costs vs measured
    latency; see the prog for the methodology)."""
    r = subprocess.run(
        [sys.executable, "-m", "mvapich2_tpu.run", "-np", "2",
         sys.executable,
         os.path.join(REPO, "tests", "progs", "trace_overhead_prog.py")],
        cwd=REPO, capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout


def test_new_nbc_entry_points_profiled():
    """Satellite: ireduce and the v-collectives are on the PMPI
    interposition surface (PROFILED_METHODS) and work end-to-end."""
    from mvapich2_tpu import profile
    for name in ("ireduce", "igatherv", "iscatterv", "iallgatherv",
                 "ialltoallv", "iscan", "ireduce_scatter_block"):
        assert name in profile.PROFILED_METHODS
        assert hasattr(__import__("mvapich2_tpu.core.comm",
                                  fromlist=["Comm"]).Comm, name)

    def body(comm):
        size, rank = comm.size, comm.rank
        out = np.zeros(size, np.float64)
        comm.iallgatherv(np.array([float(rank)]), out,
                         [1] * size).wait()
        assert out.tolist() == [float(r) for r in range(size)]
        rr = np.zeros(2, np.float64)
        comm.ireduce(np.full(2, 1.0), rr, root=0).wait()
        if rank == 0:
            assert rr[0] == size
        sc = np.zeros(1, np.float64)
        comm.iscan(np.array([1.0]), sc).wait()
        assert sc[0] == rank + 1
        rs = np.zeros(1, np.float64)
        comm.ireduce_scatter_block(np.full(size, 1.0), rs).wait()
        assert rs[0] == size
        return True

    with profile.Profiler() as prof:
        assert all(run_ranks(3, body))
    assert prof.calls["iallgatherv"] == 3
    assert prof.calls["ireduce"] == 3
    assert prof.calls["iscan"] == 3
    assert prof.calls["ireduce_scatter_block"] == 3


# -- native C-plane trace ring (ISSUE 10 tentpole) -----------------------

import shutil


def _cplane_events(merged):
    return [e for e in merged["traceEvents"]
            if e.get("ph") != "M" and e.get("cat") == "cplane"]


def test_native_ring_events_in_merged_trace(tmp_path):
    """A traced process-mode job (MV2T_NTRACE follows MV2T_TRACE)
    merges >=3 native C-plane event types into the Perfetto JSON,
    time-aligned with the python layers on the shared monotonic axis."""
    out = tmp_path / "merged.json"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "mpitrace"),
         "-np", "2", "--out", str(out), "--dir", str(tmp_path / "d"),
         sys.executable,
         os.path.join(REPO, "tests", "progs", "trace_workload_prog.py")],
        cwd=REPO, capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    merged = json.load(open(out))
    nt = _cplane_events(merged)
    names = {e["name"] for e in nt}
    assert len(names) >= 3, names
    assert {e["pid"] for e in nt} == {0, 1}
    # time-aligned: native instants land inside the job's overall span
    all_ts = [e["ts"] for e in merged["traceEvents"]
              if e.get("ph") != "M"]
    for e in nt:
        assert min(all_ts) <= e["ts"] <= max(all_ts)
        assert e["ph"] == "i"


def test_native_ring_disable_env(tmp_path):
    """MV2T_NTRACE=0 with tracing on: python layers trace, the cplane
    lane stays empty (the runtime gate works independently)."""
    env = dict(os.environ)
    env.update({"MV2T_TRACE": "1", "MV2T_TRACE_DIR": str(tmp_path),
                "MV2T_NTRACE": "0"})
    r = subprocess.run(
        [sys.executable, "-m", "mvapich2_tpu.run", "-np", "2",
         sys.executable,
         os.path.join(REPO, "tests", "progs", "trace_workload_prog.py")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    dumps = trace.read_dumps(str(tmp_path))
    assert dumps
    layers = {ev[1] for d in dumps for ev in d["events"]}
    assert "mpi" in layers and "cplane" not in layers


def test_ntrace_drain_survives_owner_unlink(tmp_path):
    """Teardown-skew regression (found as a load-dependent loss of
    ranks' cplane lanes in the mixed-ABI merge): the segment OWNER
    unlinks the .ntrace file at its close, which can precede a slower
    rank's Finalize drain. Each rank holds its own fd from attach time
    and read_ring accepts it — an unlinked-but-open inode stays
    readable, so the lane survives; the path-based read (mpistat's
    attach-from-outside mode) correctly fails once the file is gone."""
    import struct as _struct

    from mvapich2_tpu.trace import native as nt
    path = tmp_path / "ring.ntrace"
    stride = nt._NTR_HDR_BYTES + nt._NTR_RING_EVENTS * nt._NTR_EV_BYTES
    buf = bytearray(nt._NTR_FILE_HDR + stride)
    _struct.pack_into("<Q", buf, nt._NTR_FILE_HDR, 2)   # rank 0 seq=2
    ev_base = nt._NTR_FILE_HDR + nt._NTR_HDR_BYTES
    nt._REC.pack_into(buf, ev_base, 1000, 1, 0, 7, 8)
    nt._REC.pack_into(buf, ev_base + nt._NTR_EV_BYTES, 2000, 2, 1, 9, 0)
    path.write_bytes(buf)
    held = open(path, "rb")
    try:
        os.unlink(path)                      # the owner's close
        evs = nt.read_ring(held, 0)
        assert [(e[0], e[1]) for e in evs] == [(1000, 1), (2000, 2)]
        assert nt.ring_depth(held, 0) == 2
        with pytest.raises(OSError):
            nt.read_ring(str(path), 0)

        class Chan:                          # drain_channel via the fd
            plane = object()
            _ntrace_f = held
            my_rank = 0
            local_index = {0: 0}
        rows = nt.drain_channel(Chan())
        assert len(rows) == 2 and rows[0][2] == nt.event_name(1)
    finally:
        held.close()


@pytest.mark.skipif(
    __import__("shutil").which("gcc") is None
    or __import__("shutil").which("python3-config") is None,
    reason="no C toolchain")
def test_mixed_abi_merged_trace(tmp_path):
    """ISSUE 10 acceptance: a 4-rank job with C-ABI (even) + python
    (odd) ranks under MV2T_TRACE yields ONE merged Perfetto JSON where
    >=3 native C-plane event types appear on BOTH ABIs' ranks,
    correctly interleaved with python mpi spans on the shared clock."""
    import tempfile
    cbin = os.path.join(tempfile.mkdtemp(), "ntrace_cabi_test")
    r = subprocess.run(
        [os.path.join(REPO, "bin", "mpicc"),
         os.path.join(REPO, "tests", "progs", "ntrace_cabi_test.c"),
         "-o", cbin], capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, f"mpicc failed:\n{r.stdout}\n{r.stderr}"
    out = tmp_path / "mixed.json"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "mpitrace"),
         "-np", "4", "--out", str(out), "--dir", str(tmp_path / "d"),
         sys.executable,
         os.path.join(REPO, "tests", "progs", "mixed_trace_prog.py"),
         cbin],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout
    merged = json.load(open(out))
    nt = _cplane_events(merged)
    by_pid = {}
    for e in nt:
        by_pid.setdefault(e["pid"], set()).add(e["name"])
    # every rank of BOTH ABIs carries >=3 native event types
    assert set(by_pid) == {0, 1, 2, 3}, by_pid
    for pid, names in by_pid.items():
        assert len(names) >= 3, (pid, names)
    # flat waves visible across the ABI boundary: a C rank folded or
    # fanned in, a python rank fanned out of the SAME tier
    assert "flat_fanin" in by_pid[0] and "flat_fanin" in by_pid[1]
    # python ranks still carry mpi spans, on the same rebased axis
    py_mpi = [e for e in merged["traceEvents"] if e.get("ph") != "M"
              and e["cat"] == "mpi" and e["pid"] in (1, 3)]
    assert py_mpi
    lo = min(e["ts"] for e in merged["traceEvents"]
             if e.get("ph") != "M")
    hi = max(e["ts"] for e in merged["traceEvents"]
             if e.get("ph") != "M")
    for e in nt:
        assert lo <= e["ts"] <= hi


def test_watchdog_report_carries_native_tail(monkeypatch, tmp_path):
    """ISSUE 10 satellite: a stall report of a process-mode job with
    the native ring armed includes the per-rank C-plane event tail,
    region-tagged via the shared-field map."""
    env = dict(os.environ)
    env.update({"MV2T_NTRACE": "1", "MV2T_STALL_TIMEOUT": "0.5"})
    prog = tmp_path / "stall_prog.py"
    prog.write_text(
        "import sys, time\n"
        "sys.path.insert(0, '.')\n"
        "import numpy as np\n"
        "from mvapich2_tpu import mpi\n"
        "mpi.Init()\n"
        "comm = mpi.COMM_WORLD\n"
        "comm.allreduce(np.ones(8))\n"
        "if comm.rank == 0:\n"
        "    req = comm.irecv(np.zeros(4), source=1, tag=9)\n"
        "    comm.u.engine.progress_wait(lambda: req.complete_flag,\n"
        "                                timeout=8.0)\n"
        "    rep = getattr(comm.u.engine, '_stall_report', '')\n"
        "    assert 'native C-plane trace tail' in rep, rep[:2000]\n"
        "    assert 'flat_fanin' in rep or 'eager_tx' in rep, rep\n"
        "    assert '[seqlock(flat)]' in rep or '[atomic(inbox)]' in rep\n"
        "else:\n"
        "    time.sleep(2.0)\n"
        "    comm.send(np.ones(4), dest=0, tag=9)\n"
        "comm.barrier()\n"
        "if comm.rank == 0:\n"
        "    print('No Errors')\n"
        "mpi.Finalize()\n")
    r = subprocess.run(
        [sys.executable, "-m", "mvapich2_tpu.run", "-np", "2",
         sys.executable, str(prog)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout

"""Unit tests for the native pt2pt data plane (native/cplane.cpp).

Drives two plane instances over one shm segment in-process — the same
layout two rank processes share — and checks the C-side envelope matching
(ch3u_recvq.c semantics): FIFO order, wildcards, truncation, probe/mprobe,
send-cancel, unexpected-queue handling and python-inbox forwarding.
"""

import ctypes
import os
import struct
import tempfile
import uuid

import pytest

from mvapich2_tpu.transport import shm as shm_mod

PKT_HDR = struct.Struct("<Biiiiqqqq8si")
EAGER = 1
RTS = 2
FLAG = 1 << 30          # PLANE_CTX_FLAG: wire-carried ownership

RING_BYTES = 1 << 16


def _lib():
    lib = shm_mod._load_native()
    if lib is None:
        pytest.skip("native shmring unavailable")
    # plane bindings (kept local to the test; product bindings live in shm.py)
    lib.cp_create.restype = ctypes.c_void_p
    lib.cp_create.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                              ctypes.c_char_p]
    lib.cp_destroy.argtypes = [ctypes.c_void_p]
    lib.cp_ctx_enable.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.cp_ctx_disable.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.cp_inject.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p,
                              ctypes.c_long]
    lib.cp_send_eager.restype = ctypes.c_longlong
    lib.cp_send_eager.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                                  ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
                                  ctypes.c_long, ctypes.c_longlong]
    lib.cp_irecv.restype = ctypes.c_longlong
    lib.cp_irecv.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_long,
                             ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.cp_req_state.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
    lib.cp_req_status.argtypes = [ctypes.c_void_p, ctypes.c_longlong,
                                  ctypes.POINTER(ctypes.c_int),
                                  ctypes.POINTER(ctypes.c_int),
                                  ctypes.POINTER(ctypes.c_longlong),
                                  ctypes.POINTER(ctypes.c_int),
                                  ctypes.POINTER(ctypes.c_int)]
    lib.cp_req_free.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
    lib.cp_advance.argtypes = [ctypes.c_void_p]
    lib.cp_py_pending.argtypes = [ctypes.c_void_p]
    lib.cp_py_peek.restype = ctypes.c_long
    lib.cp_py_peek.argtypes = [ctypes.c_void_p]
    lib.cp_py_pop.restype = ctypes.c_long
    lib.cp_py_pop.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long]
    lib.cp_assist_pending.argtypes = [ctypes.c_void_p]
    lib.cp_assist_pop.restype = ctypes.c_long
    lib.cp_assist_pop.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_longlong),
                                  ctypes.c_char_p, ctypes.c_long]
    lib.cp_complete_assist.argtypes = [ctypes.c_void_p, ctypes.c_longlong,
                                       ctypes.c_longlong, ctypes.c_int,
                                       ctypes.c_int, ctypes.c_int]
    lib.cp_probe.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                             ctypes.c_int, ctypes.c_int,
                             ctypes.POINTER(ctypes.c_int),
                             ctypes.POINTER(ctypes.c_int),
                             ctypes.POINTER(ctypes.c_longlong),
                             ctypes.POINTER(ctypes.c_longlong)]
    lib.cp_mrecv_start.restype = ctypes.c_longlong
    lib.cp_mrecv_start.argtypes = [ctypes.c_void_p, ctypes.c_longlong,
                                   ctypes.c_void_p, ctypes.c_long]
    lib.cp_cancel_send.argtypes = [ctypes.c_void_p, ctypes.c_longlong,
                                   ctypes.c_int]
    lib.cp_cancel_result.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
    lib.cp_cancel_recv.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
    lib.cp_unexpected_count.argtypes = [ctypes.c_void_p]
    return lib


class Pair:
    """Two plane instances (ranks 0 and 1) over one segment."""

    def __init__(self, lib, ring_bytes=RING_BYTES):
        self.lib = lib
        self.path = os.path.join(tempfile.gettempdir(),
                                 f"cplane-test-{uuid.uuid4().hex[:8]}")
        self.r0 = lib.sr_attach(self.path.encode(), 2, ring_bytes, 1)
        self.r1 = lib.sr_attach(self.path.encode(), 2, ring_bytes, 0)
        assert self.r0 and self.r1
        self.p = [lib.cp_create(self.r0, 0, 2, b""),
                  lib.cp_create(self.r1, 1, 2, b"")]
        for cp in self.p:
            lib.cp_ctx_enable(cp, 0)

    def close(self):
        for cp in self.p:
            self.lib.cp_destroy(cp)
        for r in (self.r0, self.r1):
            self.lib.sr_detach(r)
        os.unlink(self.path)

    def status(self, rank, req):
        src = ctypes.c_int()
        tag = ctypes.c_int()
        nb = ctypes.c_longlong()
        tr = ctypes.c_int()
        ec = ctypes.c_int()
        rc = self.lib.cp_req_status(self.p[rank], req, src, tag, nb, tr, ec)
        assert rc == 0
        return src.value, tag.value, nb.value, tr.value, ec.value


@pytest.fixture
def pair():
    p = Pair(_lib())
    yield p
    p.close()


def test_eager_posted_then_send(pair):
    lib = pair.lib
    buf = ctypes.create_string_buffer(64)
    req = lib.cp_irecv(pair.p[1], buf, 64, 0, 0, 7)
    assert lib.cp_req_state(pair.p[1], req) == 0        # pending
    assert lib.cp_send_eager(pair.p[0], 1, 0, 0, 7, b"hello", 5, 11) == 0
    lib.cp_advance(pair.p[1])
    assert lib.cp_req_state(pair.p[1], req) == 2        # done
    src, tag, nb, tr, ec = pair.status(1, req)
    assert (src, tag, nb, tr, ec) == (0, 7, 5, 0, 0)
    assert buf.raw[:5] == b"hello"
    lib.cp_req_free(pair.p[1], req)


def test_eager_unexpected_then_recv(pair):
    lib = pair.lib
    lib.cp_send_eager(pair.p[0], 1, 0, 0, 3, b"abc", 3, 0)
    lib.cp_advance(pair.p[1])
    assert lib.cp_unexpected_count(pair.p[1]) == 1
    buf = ctypes.create_string_buffer(8)
    req = lib.cp_irecv(pair.p[1], buf, 8, 0, 0, 3)
    assert lib.cp_req_state(pair.p[1], req) == 2
    assert buf.raw[:3] == b"abc"


def test_wildcards_and_fifo(pair):
    lib = pair.lib
    for i in range(4):
        lib.cp_send_eager(pair.p[0], 1, 0, 0, 100 + i,
                          bytes([i]), 1, 0)
    lib.cp_advance(pair.p[1])
    # ANY_SOURCE + ANY_TAG matches in arrival order
    got = []
    for _ in range(4):
        buf = ctypes.create_string_buffer(4)
        req = lib.cp_irecv(pair.p[1], buf, 4, 0, -1, -2)
        assert lib.cp_req_state(pair.p[1], req) == 2
        _, tag, _, _, _ = pair.status(1, req)
        got.append((tag, buf.raw[0]))
    assert got == [(100, 0), (101, 1), (102, 2), (103, 3)]


def test_truncation(pair):
    lib = pair.lib
    buf = ctypes.create_string_buffer(3)
    req = lib.cp_irecv(pair.p[1], buf, 3, 0, 0, 1)
    lib.cp_send_eager(pair.p[0], 1, 0, 0, 1, b"abcdef", 6, 0)
    lib.cp_advance(pair.p[1])
    src, tag, nb, tr, _ = pair.status(1, req)
    assert (nb, tr) == (6, 1)
    assert buf.raw[:3] == b"abc"


def test_rts_assist_and_order(pair):
    """An RTS between two eagers must match in wire order."""
    lib = pair.lib
    lib.cp_send_eager(pair.p[0], 1, 0, 0, 5, b"A", 1, 0)
    rts = PKT_HDR.pack(RTS, 0, FLAG | 0, 0, 5, 1000, 77, 0, 0,
                       b"RGET\0\0\0\0", 0)
    lib.cp_inject(pair.p[0], 1, rts, len(rts))
    lib.cp_send_eager(pair.p[0], 1, 0, 0, 5, b"B", 1, 0)
    lib.cp_advance(pair.p[1])

    b1 = ctypes.create_string_buffer(4)
    r1 = lib.cp_irecv(pair.p[1], b1, 4, 0, 0, 5)
    assert lib.cp_req_state(pair.p[1], r1) == 2
    assert b1.raw[:1] == b"A"

    big = ctypes.create_string_buffer(1000)
    r2 = lib.cp_irecv(pair.p[1], big, 1000, 0, 0, 5)
    assert lib.cp_req_state(pair.p[1], r2) == 1          # assist
    rid = ctypes.c_longlong()
    blob = ctypes.create_string_buffer(256)
    n = lib.cp_assist_pop(pair.p[1], rid, blob, 256)
    assert n == PKT_HDR.size and rid.value == r2
    hdr = PKT_HDR.unpack_from(blob.raw, 0)
    assert hdr[0] == RTS and hdr[6] == 77                # sreq_id carried
    lib.cp_complete_assist(pair.p[1], r2, 1000, 0, 5, 0)
    assert lib.cp_req_state(pair.p[1], r2) == 2

    b3 = ctypes.create_string_buffer(4)
    r3 = lib.cp_irecv(pair.p[1], b3, 4, 0, 0, 5)
    assert lib.cp_req_state(pair.p[1], r3) == 2
    assert b3.raw[:1] == b"B"


def test_probe_and_mprobe(pair):
    lib = pair.lib
    src = ctypes.c_int()
    tag = ctypes.c_int()
    nb = ctypes.c_longlong()
    tok = ctypes.c_longlong()
    assert lib.cp_probe(pair.p[1], 0, -1, -2, 0, src, tag, nb, tok) == 0
    lib.cp_send_eager(pair.p[0], 1, 0, 0, 9, b"xy", 2, 0)
    lib.cp_advance(pair.p[1])
    assert lib.cp_probe(pair.p[1], 0, -1, -2, 0, src, tag, nb, tok) == 1
    assert (src.value, tag.value, nb.value) == (0, 9, 2)
    # mprobe parks it; a second probe sees nothing
    assert lib.cp_probe(pair.p[1], 0, 0, 9, 1, src, tag, nb, tok) == 1
    assert lib.cp_probe(pair.p[1], 0, -1, -2, 0, src, tag, nb, tok) == 0
    buf = ctypes.create_string_buffer(4)
    req = lib.cp_mrecv_start(pair.p[1], tok.value, buf, 4)
    assert req > 0 and lib.cp_req_state(pair.p[1], req) == 2
    assert buf.raw[:2] == b"xy"


def test_send_cancel(pair):
    lib = pair.lib
    lib.cp_send_eager(pair.p[0], 1, 0, 0, 4, b"zz", 2, 555)
    lib.cp_advance(pair.p[1])        # lands unexpected at rank 1
    lib.cp_cancel_send(pair.p[0], 555, 1)
    lib.cp_advance(pair.p[1])        # target retracts, responds
    lib.cp_advance(pair.p[0])        # origin sees the RESP
    assert lib.cp_cancel_result(pair.p[0], 555) == 1
    assert lib.cp_unexpected_count(pair.p[1]) == 0
    # cancelling an already-matched send fails cleanly
    lib.cp_send_eager(pair.p[0], 1, 0, 0, 4, b"qq", 2, 556)
    buf = ctypes.create_string_buffer(4)
    lib.cp_advance(pair.p[1])
    req = lib.cp_irecv(pair.p[1], buf, 4, 0, 0, 4)
    assert lib.cp_req_state(pair.p[1], req) == 2
    lib.cp_cancel_send(pair.p[0], 556, 1)
    lib.cp_advance(pair.p[1])
    # already matched: the plane forwards the REQ to the python matcher,
    # which replies "not retracted" (protocol.py _on_cancel_req). Emulate.
    assert lib.cp_py_pending(pair.p[1]) == 1
    n = lib.cp_py_peek(pair.p[1])
    raw = ctypes.create_string_buffer(n)
    lib.cp_py_pop(pair.p[1], raw, n)
    assert PKT_HDR.unpack_from(raw.raw, 0)[0] == 33      # CANCEL_SEND_REQ
    resp = PKT_HDR.pack(34, 1, 0, 0, 0, 0, 556, 0, 0, b"\0" * 8, 0)
    lib.cp_inject(pair.p[1], 0, resp, len(resp))
    lib.cp_advance(pair.p[0])
    assert lib.cp_cancel_result(pair.p[0], 556) == 0


def test_python_inbox_forwarding(pair):
    """Unflagged eager (python-owned ctx) and unknown packet types bypass
    the C matcher; flagged eager is claimed by it."""
    lib = pair.lib
    # python-owned eager: NO ownership flag on the wire
    e = PKT_HDR.pack(EAGER, 0, 42, 0, 3, 1, 0, 0, 0, b"\0" * 8, 0) + b"d"
    lib.cp_inject(pair.p[0], 1, e, len(e))
    blob = PKT_HDR.pack(30, 0, 0, 0, 0, 0, 0, 0, 0, b"\0" * 8, 0)  # BARRIER
    lib.cp_inject(pair.p[0], 1, blob, len(blob))
    # plane-owned eager (cp_send_eager flags the wire): C-matched
    lib.cp_send_eager(pair.p[0], 1, 42, 0, 3, b"c", 1, 0)
    lib.cp_advance(pair.p[1])
    assert lib.cp_py_pending(pair.p[1]) == 2
    seen = []
    while lib.cp_py_pending(pair.p[1]):
        n = lib.cp_py_peek(pair.p[1])
        buf = ctypes.create_string_buffer(n)
        assert lib.cp_py_pop(pair.p[1], buf, n) == n
        seen.append(PKT_HDR.unpack_from(buf.raw, 0)[0])
    assert seen == [EAGER, 30]
    assert lib.cp_unexpected_count(pair.p[1]) == 1


def test_backlog_ring_full(pair):
    """Flood past ring capacity; the C backlog preserves FIFO + no loss."""
    lib = pair.lib
    n = 2000
    payload = b"p" * 100
    for i in range(n):
        assert lib.cp_send_eager(pair.p[0], 1, 0, 0, i, payload, 100, 0) == 0
    got = 0
    buf = ctypes.create_string_buffer(128)
    while got < n:
        lib.cp_advance(pair.p[1])
        lib.cp_advance(pair.p[0])    # flushes origin backlog
        req = lib.cp_irecv(pair.p[1], buf, 128, 0, 0, got)
        if lib.cp_req_state(pair.p[1], req) == 2:
            got += 1
        lib.cp_req_free(pair.p[1], req)
    assert got == n


def test_self_send(pair):
    lib = pair.lib
    lib.cp_send_eager(pair.p[0], 0, 0, 0, 2, b"me", 2, 0)
    lib.cp_advance(pair.p[0])
    buf = ctypes.create_string_buffer(4)
    req = lib.cp_irecv(pair.p[0], buf, 4, 0, 0, 2)
    assert lib.cp_req_state(pair.p[0], req) == 2
    assert buf.raw[:2] == b"me"


def test_cancel_recv(pair):
    lib = pair.lib
    buf = ctypes.create_string_buffer(4)
    req = lib.cp_irecv(pair.p[1], buf, 4, 0, 0, 88)
    assert lib.cp_cancel_recv(pair.p[1], req) == 1
    assert lib.cp_req_state(pair.p[1], req) == 2
    # message sent after the cancel stays unexpected
    lib.cp_send_eager(pair.p[0], 1, 0, 0, 88, b"x", 1, 0)
    lib.cp_advance(pair.p[1])
    assert lib.cp_unexpected_count(pair.p[1]) == 1


def test_orphaned_recv_still_completes(pair):
    """MPI_Request_free on an active receive: the operation must still
    complete into the user buffer (the request reclaims itself)."""
    lib = pair.lib
    lib.cp_req_orphan.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
    buf = ctypes.create_string_buffer(16)
    req = lib.cp_irecv(pair.p[1], buf, 16, 0, 0, 42)
    lib.cp_req_orphan(pair.p[1], req)
    # the slot is gone from the owner's view...
    assert lib.cp_req_state(pair.p[1], req) in (0, 3)
    # ...but a matching inbound message still lands in the user buffer
    assert lib.cp_send_eager(pair.p[0], 1, 0, 0, 42, b"orphan!", 7, 0) == 0
    lib.cp_advance(pair.p[1])
    assert buf.raw[:7] == b"orphan!"
    # and the plane request slot was reclaimed (state reads FREE)
    assert lib.cp_req_state(pair.p[1], req) == 3
    # nothing was diverted to the unexpected queue
    assert lib.cp_unexpected_count(pair.p[1]) == 0


def test_ctx_disable_semantics(pair):
    """cp_ctx_disable drops unmatched unexpected entries and future
    unmatched traffic, but already-matched work survives: mprobe-parked
    tokens stay receivable (Mprobe -> Comm_free -> Mrecv is legal) and
    posted receives still complete (MPI-3.1 §6.4.3 deferred free)."""
    lib = pair.lib
    lib.cp_send_eager(pair.p[0], 1, 0, 0, 5, b"aa", 2, 0)
    lib.cp_send_eager(pair.p[0], 1, 0, 0, 6, b"bb", 2, 0)
    lib.cp_advance(pair.p[1])
    src = ctypes.c_int()
    tag = ctypes.c_int()
    nb = ctypes.c_longlong()
    tok = ctypes.c_longlong()
    # park one entry via mprobe; post a recv for a third message
    assert lib.cp_probe(pair.p[1], 0, 0, 5, 1, src, tag, nb, tok) == 1
    assert lib.cp_unexpected_count(pair.p[1]) == 1
    pbuf = ctypes.create_string_buffer(8)
    posted = lib.cp_irecv(pair.p[1], pbuf, 8, 0, 0, 7)
    lib.cp_ctx_disable(pair.p[1], 0)
    # unmatched unexpected entry purged
    assert lib.cp_unexpected_count(pair.p[1]) == 0
    # the parked token survives: mrecv still delivers the bytes
    buf = ctypes.create_string_buffer(8)
    req = lib.cp_mrecv_start(pair.p[1], tok.value, buf, 8)
    assert req >= 0 and buf.raw[:2] == b"aa"
    # a pending posted recv on the retired ctx still completes
    assert lib.cp_send_eager(pair.p[0], 1, 0, 0, 7, b"cc", 2, 0) == 0
    lib.cp_advance(pair.p[1])
    assert lib.cp_req_state(pair.p[1], posted) == 2
    assert pbuf.raw[:2] == b"cc"
    # fresh unmatched traffic for the retired ctx QUEUES: context ids
    # are reused (MPIR-style mask allocator), and the first collective
    # on a reused id races the slower members' re-enable — queuing is
    # what keeps that collective alive. The freed-comm leak is handled
    # by the purge at disable time (asserted above); a re-disable
    # collects any stragglers.
    lib.cp_send_eager(pair.p[0], 1, 0, 0, 99, b"zz", 2, 0)
    lib.cp_advance(pair.p[1])
    assert lib.cp_unexpected_count(pair.p[1]) == 1
    lib.cp_ctx_disable(pair.p[1], 0)
    assert lib.cp_unexpected_count(pair.p[1]) == 0
    # and cp_ctx_enable (comm creation on a reused id) resets the
    # collective-tag counter so members restart in lockstep
    lib.cp_ctx_enable(pair.p[1], 0)


def _bind_cma(lib):
    lib.cp_set_cma.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.cp_cma_enabled.argtypes = [ctypes.c_void_p]
    lib.cp_send_rndv.restype = ctypes.c_longlong
    lib.cp_send_rndv.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                 ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                 ctypes.c_void_p, ctypes.c_longlong]


def test_cma_rndv_posted_then_send(pair):
    """CMA rendezvous: receiver pulls straight from the sender's buffer
    at match time and FINs; sender request completes."""
    lib = pair.lib
    _bind_cma(lib)
    for cp in pair.p:
        lib.cp_set_cma(cp, 1)
    n = 256 * 1024
    payload = bytes(range(256)) * 1024
    sbuf = ctypes.create_string_buffer(payload, n)
    rbuf = ctypes.create_string_buffer(n)
    rreq = lib.cp_irecv(pair.p[1], rbuf, n, 0, 0, 7)
    sreq = lib.cp_send_rndv(pair.p[0], 1, 0, 0, 7, sbuf, n)
    assert sreq > 0
    lib.cp_advance(pair.p[1])        # receiver matches + pulls + FINs
    assert lib.cp_req_state(pair.p[1], rreq) == 2
    assert rbuf.raw[:n] == payload
    lib.cp_advance(pair.p[0])        # sender sees the FIN
    assert lib.cp_req_state(pair.p[0], sreq) == 2
    src, tag, nb, tr, ec = pair.status(1, rreq)
    assert (src, tag, nb, tr, ec) == (0, 7, n, 0, 0)
    lib.cp_req_free(pair.p[1], rreq)
    lib.cp_req_free(pair.p[0], sreq)


def test_cma_rndv_unexpected_then_recv(pair):
    """RTS_CMA arriving before the recv parks as unexpected; the pull
    happens at irecv time. Probe sees it as a rendezvous."""
    lib = pair.lib
    _bind_cma(lib)
    for cp in pair.p:
        lib.cp_set_cma(cp, 1)
    n = 100 * 1000
    payload = b"\xab" * n
    sbuf = ctypes.create_string_buffer(payload, n)
    sreq = lib.cp_send_rndv(pair.p[0], 1, 0, 0, 9, sbuf, n)
    lib.cp_advance(pair.p[1])
    assert lib.cp_unexpected_count(pair.p[1]) == 1
    src = ctypes.c_int()
    tag = ctypes.c_int()
    nb = ctypes.c_longlong()
    tok = ctypes.c_longlong()
    assert lib.cp_probe(pair.p[1], 0, -1, -2, 0, src, tag, nb, tok) == 2
    assert nb.value == n
    rbuf = ctypes.create_string_buffer(n)
    rreq = lib.cp_irecv(pair.p[1], rbuf, n, 0, 0, 9)
    assert lib.cp_req_state(pair.p[1], rreq) == 2
    assert rbuf.raw[:n] == payload
    lib.cp_advance(pair.p[0])
    assert lib.cp_req_state(pair.p[0], sreq) == 2
    lib.cp_req_free(pair.p[1], rreq)
    lib.cp_req_free(pair.p[0], sreq)


def test_cma_rndv_truncation(pair):
    """Receiver buffer smaller than the message: clamp + truncated."""
    lib = pair.lib
    _bind_cma(lib)
    for cp in pair.p:
        lib.cp_set_cma(cp, 1)
    sbuf = ctypes.create_string_buffer(b"x" * 1000, 1000)
    rbuf = ctypes.create_string_buffer(100)
    rreq = lib.cp_irecv(pair.p[1], rbuf, 100, 0, 0, 3)
    sreq = lib.cp_send_rndv(pair.p[0], 1, 0, 0, 3, sbuf, 1000)
    lib.cp_advance(pair.p[1])
    src, tag, nb, tr, ec = pair.status(1, rreq)
    assert (nb, tr) == (1000, 1)
    assert rbuf.raw[:100] == b"x" * 100
    lib.cp_advance(pair.p[0])
    assert lib.cp_req_state(pair.p[0], sreq) == 2   # sender released
    lib.cp_req_free(pair.p[1], rreq)
    lib.cp_req_free(pair.p[0], sreq)


def test_cma_disabled_send_rejected(pair):
    lib = pair.lib
    _bind_cma(lib)
    sbuf = ctypes.create_string_buffer(64)
    assert lib.cp_send_rndv(pair.p[0], 1, 0, 0, 1, sbuf, 64) == -1

"""Warm-attach node daemon (runtime/daemon.py) + churn bench smoke.

Unit level: claim/release/epoch protocol, versioned handshake, reset
zeroing, stale-epoch sweep. End to end: two sequential jobs with
MV2T_DAEMON=1 reuse the same segment set (warm attach), and the churn
bench (mvapich2_tpu.bench.churn) stays wired."""

import json
import os
import shutil
import subprocess
import sys
import tempfile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from mvapich2_tpu.runtime import daemon  # noqa: E402


@pytest.fixture()
def ddir(monkeypatch):
    d = tempfile.mkdtemp(prefix="mv2t-daemon-test-")
    # unit tests drive the manifest protocol directly — no serve loop
    monkeypatch.setenv("MV2T_DAEMON_SPAWN", "0")
    from mvapich2_tpu.utils.config import get_config
    get_config().reload()
    yield d
    shutil.rmtree(d, ignore_errors=True)


def test_claim_creates_and_epochs(ddir):
    c = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    assert c is not None and c.epoch == 1
    # flags = pad8(2) + 2 lease stamps + 2 x 16 fpc-mirror slots
    # (runtime/boot.py flags_len — the ISSUE 10 counter tail)
    for p, want in ((c.ring, 4 << 20), (c.flags, 8 + 16 + 256),
                    (c.flat, 0), (c.arena, 4096 + 2 * (1 << 20))):
        assert os.path.getsize(p) == want, p
    # busy set with a live owner is not claimable
    assert daemon.claim(2, 1 << 20, 1 << 20, ddir) is None
    daemon.release(c)
    c2 = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    assert c2 is not None and c2.epoch == 2
    daemon.release(c2)


def test_claim_resets_previous_epoch(ddir):
    c = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    with open(c.ring, "r+b") as f:
        f.write(b"\xab" * 4096)   # stale protocol words from this epoch
    daemon.release(c)
    c2 = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    with open(c2.ring, "rb") as f:
        assert f.read(4096) == b"\x00" * 4096, \
            "claim must never expose the previous epoch's words"
    daemon.release(c2)


def test_stale_epoch_sweep(ddir):
    c = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    # simulate a SIGKILLed owner: mark the set busy under a dead pid
    with daemon._manifest_txn(ddir) as m:
        m["sets"][c.geokey]["owner_pid"] = 2 ** 22 + 12345
    assert daemon.sweep(ddir) == 1
    c2 = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    assert c2 is not None and c2.epoch == c.epoch + 1
    daemon.release(c2)


def test_dead_owner_reclaimed_at_claim(ddir):
    c = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    with daemon._manifest_txn(ddir) as m:
        m["sets"][c.geokey]["owner_pid"] = 2 ** 22 + 54321
    # no sweep in between: the claim itself reclaims the stale epoch
    c2 = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    assert c2 is not None and c2.epoch == c.epoch + 1
    daemon.release(c2)


def test_version_handshake_refuses_mismatch(ddir):
    c = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    daemon.release(c)
    with daemon._manifest_txn(ddir) as m:
        m["version"] = daemon.MANIFEST_VERSION + 1
    assert daemon.claim(2, 1 << 20, 1 << 20, ddir) is None


def test_geometry_keys_are_disjoint(ddir):
    a = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    b = daemon.claim(4, 1 << 20, 1 << 20, ddir)
    assert a is not None and b is not None
    assert a.geokey != b.geokey and a.ring != b.ring
    daemon.release(a)
    daemon.release(b)


def test_status_cli(ddir):
    c = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    st = daemon.status(ddir)
    assert st["sets"][c.geokey]["state"] == "busy"
    assert st["daemon_alive"] is False
    daemon.release(c)


def _run_job(env_extra, argv, timeout=300):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-m", "mvapich2_tpu.run", "-np", "2", *argv],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=timeout)


def test_warm_attach_two_jobs_reuse_segments(tmp_path):
    """End to end: two sequential np2 jobs with MV2T_DAEMON=1 share one
    segment set (epoch 1 then 2), and the second job's collectives are
    correct on the reused (reset) segments."""
    d = str(tmp_path / "dd")
    prog = os.path.join(REPO, "tests", "progs", "lazywire_prog.py")
    env = {"MV2T_DAEMON": "1", "MV2T_DAEMON_DIR": d,
           "MV2T_DAEMON_SPAWN": "0"}
    for i in (1, 2):
        r = _run_job(env, [sys.executable, prog, "flat"])
        assert r.returncode == 0, \
            f"job {i}: stdout={r.stdout}\nstderr={r.stderr}"
        assert "No Errors" in r.stdout
    with open(os.path.join(d, "manifest.json")) as f:
        m = json.load(f)
    sets = list(m["sets"].values())
    assert len(sets) == 1, "both jobs must reuse ONE geometry set"
    assert sets[0]["epoch"] == 2
    assert sets[0]["state"] == "free"


def test_daemon_off_is_default_path(tmp_path):
    """MV2T_DAEMON unset: no daemon dir is created or touched."""
    d = str(tmp_path / "dd")
    prog = os.path.join(REPO, "tests", "progs", "lazywire_prog.py")
    r = _run_job({"MV2T_DAEMON_DIR": d}, [sys.executable, prog, "flat"])
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert not os.path.exists(d)


def test_churn_smoke(tmp_path):
    """Tier-1 churn-bench smoke: a few Init/Finalize cycles complete
    through the launcher with the daemon on and off, and report a
    positive cycles/s (the full measurement lives in bin/bench_osu)."""
    from mvapich2_tpu.bench.churn import churn_rate
    prog = os.path.join(REPO, "tests", "progs", "churn_cycle_prog.py")
    env = {"MV2T_DAEMON_DIR": str(tmp_path / "dd"),
           "MV2T_DAEMON_SPAWN": "0", "JAX_PLATFORMS": "cpu"}
    for dm in (0, 1):
        r = churn_rate([sys.executable, prog], np_=2, cycles=2,
                       daemon=dm, env_extra=env, timeout=240)
        assert r["cps"] > 0 and r["cycles"] == 2, r


def test_serve_loop_idle_expiry(ddir):
    """The serve loop exits after the idle timeout and unlinks free
    sets (run with a subsecond budget; no background daemon left)."""
    c = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    daemon.release(c)
    rc = subprocess.run(
        [sys.executable, "-m", "mvapich2_tpu.runtime.daemon", "--serve",
         "--dir", ddir, "--idle", "0.1"],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert rc.returncode == 0, rc.stderr
    assert not os.path.exists(c.ring)
    with open(os.path.join(ddir, "manifest.json")) as f:
        m = json.load(f)
    assert m["daemon_pid"] == 0 and m["sets"] == {}

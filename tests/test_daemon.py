"""Multi-tenant warm-attach node daemon (runtime/daemon.py) + churn
bench smoke.

Unit level: claim/release/epoch protocol, per-geometry set instances
under the admission quota, bounded FIFO claim queue, versioned
handshake (v2 upgrade-in-place, future refusal), reset zeroing,
stale-epoch sweep, crash-mid-claim recovery (MV2T_FAULTS=claim:crash),
exec-cache hit/miss/invalidation, SCM_RIGHTS listener handoff.

End to end: two OVERLAPPING jobs of different geometries warm-attach
concurrently from one daemon; the serve loop idle-expires without ever
reaping a held set (the no-reap-under-concurrency regression); the
churn bench (serial + concurrent) stays wired. The full overlap matrix
at higher job counts rides the ``chaos`` marker.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from mvapich2_tpu.runtime import daemon  # noqa: E402


def _reload(**env):
    for k, v in env.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    from mvapich2_tpu.utils.config import get_config
    get_config().reload()


@pytest.fixture()
def ddir():
    d = tempfile.mkdtemp(prefix="mv2t-daemon-test-")
    # unit tests drive the manifest protocol directly — no serve loop
    _reload(MV2T_DAEMON_SPAWN="0")
    yield d
    _reload(MV2T_DAEMON_SPAWN=None, MV2T_DAEMON_NSETS=None,
            MV2T_DAEMON_QUOTA=None, MV2T_DAEMON=None,
            MV2T_DAEMON_EXEC_CACHE=None, MV2T_DAEMON_DIR=None)
    shutil.rmtree(d, ignore_errors=True)


def test_claim_creates_and_epochs(ddir):
    c = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    assert c is not None and c.epoch == 1
    assert c.setkey == f"{c.geokey}-i0"
    # flags = pad8(2) + 2 lease stamps + 2 x 16 fpc-mirror slots
    # (runtime/boot.py flags_len — the ISSUE 10 counter tail)
    for p, want in ((c.ring, 4 << 20), (c.flags, 8 + 16 + 256),
                    (c.flat, 0), (c.arena, 4096 + 2 * (1 << 20))):
        assert os.path.getsize(p) == want, p
    daemon.release(c)
    c2 = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    assert c2 is not None and c2.epoch == 2
    assert c2.setkey == c.setkey, "released instance is reused"
    daemon.release(c2)


def test_concurrent_claims_same_geometry(ddir):
    """The multi-tenant core: a second overlapping job of the SAME
    geometry claims a second set instance instead of serializing."""
    a = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    b = daemon.claim(2, 1 << 20, 1 << 20, ddir, wait_s=0.2)
    assert a is not None and b is not None
    assert a.geokey == b.geokey and a.setkey != b.setkey
    assert a.ring != b.ring, "instances must map disjoint files"
    daemon.release(a)
    daemon.release(b)


def test_nsets_bound_queues_then_times_out(ddir):
    """Instances are bounded by MV2T_DAEMON_NSETS: past the bound a
    claim queues (daemon_queue_waits pvar) and times out to None —
    private segments, never an error."""
    from mvapich2_tpu import mpit
    _reload(MV2T_DAEMON_NSETS="1")
    waits0 = mpit.pvar("daemon_queue_waits").read()
    a = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    assert a is not None
    b = daemon.claim(2, 1 << 20, 1 << 20, ddir, wait_s=0.2)
    assert b is None
    assert mpit.pvar("daemon_queue_waits").read() == waits0 + 1
    with open(os.path.join(ddir, "manifest.json")) as f:
        assert json.load(f)["queue"] == [], "timed-out waiter dequeued"
    daemon.release(a)


def test_quota_queues_and_grants_on_release(ddir):
    """A claim past MV2T_DAEMON_QUOTA parks in the FIFO queue and is
    granted when capacity frees (the no-hang shape)."""
    _reload(MV2T_DAEMON_QUOTA="1")
    a = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    assert a is not None
    got = {}

    def waiter():
        got["cl"] = daemon.claim(3, 1 << 20, 1 << 20, ddir, wait_s=10)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.15)          # the waiter is parked in the queue
    daemon.release(a)
    t.join(timeout=15)
    assert got["cl"] is not None, "queued waiter was never granted"
    daemon.release(got["cl"])


def test_claim_resets_previous_epoch(ddir):
    c = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    with open(c.ring, "r+b") as f:
        f.write(b"\xab" * 4096)   # stale protocol words from this epoch
    daemon.release(c)
    c2 = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    with open(c2.ring, "rb") as f:
        assert f.read(4096) == b"\x00" * 4096, \
            "claim must never expose the previous epoch's words"
    daemon.release(c2)


def test_stale_epoch_sweep(ddir):
    c = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    # simulate a SIGKILLed owner: mark the set busy under a dead pid
    with daemon._manifest_txn(ddir) as m:
        m["sets"][c.setkey]["owner_pid"] = 2 ** 22 + 12345
    assert daemon.sweep(ddir) == 1
    c2 = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    assert c2 is not None and c2.epoch == c.epoch + 1
    daemon.release(c2)


def test_dead_owner_reclaimed_at_claim(ddir):
    """No sweep in between: with every instance held by dead owners
    (NSETS=1 pins one instance), the claim itself reclaims the stale
    epoch."""
    _reload(MV2T_DAEMON_NSETS="1")
    c = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    with daemon._manifest_txn(ddir) as m:
        m["sets"][c.setkey]["owner_pid"] = 2 ** 22 + 54321
    c2 = daemon.claim(2, 1 << 20, 1 << 20, ddir, wait_s=2)
    assert c2 is not None and c2.epoch == c.epoch + 1
    assert c2.setkey == c.setkey
    daemon.release(c2)


def test_crash_mid_claim_recovery(ddir):
    """MV2T_FAULTS=claim:crash kills the claimer between the grant
    transaction and its attach — the exact window the stale-epoch
    sweep must recover. The next claim reclaims the set."""
    code = (
        "from mvapich2_tpu.utils.config import get_config\n"
        "get_config().reload()\n"
        "from mvapich2_tpu import faults\n"
        "faults.configure(0)\n"
        "from mvapich2_tpu.runtime import daemon\n"
        f"daemon.claim(2, 1 << 20, 1 << 20, {ddir!r})\n"
        "raise SystemExit('fault did not fire')\n")
    r = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 MV2T_FAULTS="claim:crash", MV2T_DAEMON_SPAWN="0"),
        capture_output=True, text=True)
    assert r.returncode == 17, f"crash kind exits 17: {r.stderr}"
    with open(os.path.join(ddir, "manifest.json")) as f:
        s = list(json.load(f)["sets"].values())[0]
    assert s["state"] == "busy" and not daemon._alive(s["owner_pid"])
    c = daemon.claim(2, 1 << 20, 1 << 20, ddir, wait_s=2)
    assert c is not None and c.epoch == 2, \
        "stale epoch of the crashed claimer must be reclaimed"
    daemon.release(c)


def test_version_handshake_refuses_future(ddir):
    c = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    daemon.release(c)
    with daemon._manifest_txn(ddir) as m:
        m["version"] = daemon.MANIFEST_VERSION + 1
    assert daemon.claim(2, 1 << 20, 1 << 20, ddir, wait_s=0.2) is None


def test_v2_manifest_upgraded_in_place(ddir):
    """A pre-multi-tenant (v2) manifest is adopted under the flock:
    sets re-key to instance 0, epochs survive, v3 fields appear."""
    geo = "n2-r1048576-p1048576"
    files = {k: os.path.join(ddir, f"{geo}.{k}")
             for k in ("ring", "flags", "flat", "flat2", "arena")}
    for p in files.values():
        open(p, "wb").close()
    with open(os.path.join(ddir, "manifest.json"), "w") as f:
        json.dump({"version": 2, "daemon_pid": 0, "sets": {
            geo: {"state": "free", "epoch": 7, "owner_pid": 0,
                  "files": files,
                  "sizes": {k: 0 for k in files}}}}, f)
    c = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    assert c is not None and c.setkey == f"{geo}-i0" and c.epoch == 8
    with open(os.path.join(ddir, "manifest.json")) as f:
        m = json.load(f)
    assert m["version"] == daemon.MANIFEST_VERSION
    assert "exec_epoch" in m and "queue" in m
    daemon.release(c)


def test_geometry_keys_are_disjoint(ddir):
    a = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    b = daemon.claim(4, 1 << 20, 1 << 20, ddir)
    assert a is not None and b is not None
    assert a.geokey != b.geokey and a.ring != b.ring
    daemon.release(a)
    daemon.release(b)


def test_status_cli(ddir):
    c = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    st = daemon.status(ddir)
    assert st["sets"][c.setkey]["state"] == "busy"
    assert st["daemon_alive"] is False
    assert "exec_cache" in st
    daemon.release(c)


# -- executable cache ----------------------------------------------------

def test_exec_cache_hit_miss_invalidation(ddir):
    """The epoch discipline applied to executables: get/put roundtrip,
    key separation, and a reset (epoch bump) that makes every old
    entry a miss — with the hits/misses/bytes pvars counting."""
    from mvapich2_tpu import mpit
    h0 = mpit.pvar("exec_cache_hits").read()
    m0 = mpit.pvar("exec_cache_misses").read()
    assert daemon.exec_cache_get("k1", ddir) is None          # miss
    assert daemon.exec_cache_put("k1", b"artifact-1", ddir)
    assert daemon.exec_cache_get("k1", ddir) == b"artifact-1"  # hit
    assert daemon.exec_cache_get("k2", ddir) is None           # miss
    assert mpit.pvar("exec_cache_hits").read() == h0 + 1
    assert mpit.pvar("exec_cache_misses").read() == m0 + 2
    assert mpit.pvar("exec_cache_bytes").read() >= 10
    old_epoch = daemon.exec_cache_epoch(ddir)
    assert daemon.exec_cache_reset(ddir) == old_epoch + 1
    assert daemon.exec_cache_get("k1", ddir) is None, \
        "a stale-epoch artifact must never be served"
    st = daemon.exec_cache_stats(ddir)
    assert st["entries"] == 0, "reset sweeps the stale files"


def test_exec_cache_gating(ddir):
    """exec_cache_enabled follows MV2T_DAEMON + MV2T_DAEMON_EXEC_CACHE
    (the coll/device.py builds consult it before touching the dir)."""
    _reload(MV2T_DAEMON=None, MV2T_DAEMON_EXEC_CACHE=None)
    assert not daemon.exec_cache_enabled()
    _reload(MV2T_DAEMON="1")
    assert daemon.exec_cache_enabled()
    _reload(MV2T_DAEMON_EXEC_CACHE="0")
    assert not daemon.exec_cache_enabled()


def test_exec_cache_device_build_roundtrip(ddir):
    """End to end through coll/device.py: the first device-collective
    program build of a 'process' populates the cache, a fresh channel
    (the next process) hits it, and an epoch reset invalidates — on
    the CPU/interpreter path of this host."""
    import numpy as np

    from mvapich2_tpu import mpit
    from mvapich2_tpu.runtime.universe import run_ranks
    # force the device transport: the committed CPU tuning profile
    # routes host-staged buffers to the host path at these sizes, and
    # this test is about the BUILD cost, not the crossover
    _reload(MV2T_DAEMON="1", MV2T_DAEMON_DIR=ddir,
            MV2T_DAEMON_EXEC_CACHE="1", MV2T_ALLREDUCE_ALGO="device")

    def app(comm):
        x = np.full(16384, float(comm.rank + 1), np.float32)
        out = comm.allreduce(x)
        assert out[0] == sum(range(1, comm.size + 1))

    h0 = mpit.pvar("exec_cache_hits").read()
    run_ranks(4, app, device_mesh=True)
    assert daemon.exec_cache_stats(ddir)["entries"] >= 1, \
        "first build must populate the cache"
    run_ranks(4, app, device_mesh=True)   # fresh channels: cache hit
    assert mpit.pvar("exec_cache_hits").read() > h0
    daemon.exec_cache_reset(ddir)
    m0 = mpit.pvar("exec_cache_misses").read()
    run_ranks(4, app, device_mesh=True)
    assert mpit.pvar("exec_cache_misses").read() > m0, \
        "epoch reset must invalidate (miss + repopulate)"
    _reload(MV2T_DAEMON_DIR=None, MV2T_ALLREDUCE_ALGO=None)


# -- listener handoff ----------------------------------------------------

def test_take_listener_scm_rights(ddir):
    """The serve loop hands a pre-bound listening TCP socket over
    SCM_RIGHTS; without a daemon the call returns None (private bind,
    bit-identical to MV2T_DAEMON=0)."""
    import socket as socketlib
    assert daemon.take_listener(ddir) is None    # nobody serving
    p = subprocess.Popen(
        [sys.executable, "-m", "mvapich2_tpu.runtime.daemon",
         "--serve", "--dir", ddir, "--idle", "60"],
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 MV2T_DAEMON_SPAWN="0"))
    try:
        sock_path = os.path.join(ddir, "daemon.sock")
        for _ in range(200):
            if os.path.exists(sock_path):
                break
            time.sleep(0.05)
        lst = daemon.take_listener(ddir, geokey="n2-test")
        assert lst is not None, "daemon must serve a listener"
        host, port = lst.getsockname()[:2]
        assert port > 0
        c = socketlib.create_connection((host, port), timeout=5)
        conn, _ = lst.accept()
        conn.sendall(b"ok")
        assert c.recv(2) == b"ok"
        c.close()
        conn.close()
        lst.close()
    finally:
        subprocess.run(
            [sys.executable, "-m", "mvapich2_tpu.runtime.daemon",
             "--stop", "--dir", ddir],
            env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=60)
        p.wait(timeout=30)


# -- serve loop: expiry is concurrency-safe ------------------------------

def test_serve_loop_idle_expiry(ddir):
    """The serve loop exits after the idle timeout and unlinks free
    sets (run with a subsecond budget; no background daemon left)."""
    c = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    daemon.release(c)
    rc = subprocess.run(
        [sys.executable, "-m", "mvapich2_tpu.runtime.daemon", "--serve",
         "--dir", ddir, "--idle", "0.1"],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert rc.returncode == 0, rc.stderr
    assert not os.path.exists(c.ring)
    with open(os.path.join(ddir, "manifest.json")) as f:
        m = json.load(f)
    assert m["daemon_pid"] == 0 and m["sets"] == {}


def test_serve_never_reaps_held_set(ddir):
    """The no-reap-under-concurrency regression (model mutation
    expiry_checks_set0): the serve loop's idle-exit teardown
    (daemon._expire_idle — the exact code serve() runs) must leave a
    held set intact even when free sibling sets in the same manifest
    made the daemon decide to expire; only the free siblings go."""
    held = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    free = daemon.claim(3, 1 << 20, 1 << 20, ddir)
    daemon.release(free)
    with daemon._manifest_txn(ddir) as m:
        m["daemon_pid"] = os.getpid()    # adopt as the serving daemon
    assert daemon._expire_idle(ddir, os.getpid())
    assert os.path.exists(held.ring), \
        "expiry reaped a live job's segment files"
    with open(os.path.join(ddir, "manifest.json")) as f:
        m = json.load(f)
    assert held.setkey in m["sets"], "held set must survive expiry"
    assert free.setkey not in m["sets"], "free sibling is expired"
    assert not os.path.exists(free.ring)
    daemon.release(held)


def test_serve_stays_up_while_held_or_queued(ddir):
    """Idle expiry must not fire while a set is held: a serve with a
    tiny idle budget keeps running until the claim is released."""
    c = daemon.claim(2, 1 << 20, 1 << 20, ddir)
    p = subprocess.Popen(
        [sys.executable, "-m", "mvapich2_tpu.runtime.daemon", "--serve",
         "--dir", ddir, "--idle", "0.6"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    try:
        time.sleep(2.5)
        assert p.poll() is None, \
            "serve idle-expired while a claim was held"
        assert os.path.exists(c.ring)
        daemon.release(c)
        p.wait(timeout=60)
        assert p.returncode == 0
        assert not os.path.exists(c.ring), "released set expired"
    finally:
        if p.poll() is None:
            p.kill()


# -- end to end ----------------------------------------------------------

def _run_job(env_extra, argv, np_=2, timeout=300):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-m", "mvapich2_tpu.run", "-np", str(np_),
         *argv],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=timeout)


def test_warm_attach_two_jobs_reuse_segments(tmp_path):
    """End to end: two sequential np2 jobs with MV2T_DAEMON=1 share one
    segment set (epoch 1 then 2), and the second job's collectives are
    correct on the reused (reset) segments."""
    d = str(tmp_path / "dd")
    prog = os.path.join(REPO, "tests", "progs", "lazywire_prog.py")
    env = {"MV2T_DAEMON": "1", "MV2T_DAEMON_DIR": d,
           "MV2T_DAEMON_SPAWN": "0"}
    for i in (1, 2):
        r = _run_job(env, [sys.executable, prog, "flat"])
        assert r.returncode == 0, \
            f"job {i}: stdout={r.stdout}\nstderr={r.stderr}"
        assert "No Errors" in r.stdout
    with open(os.path.join(d, "manifest.json")) as f:
        m = json.load(f)
    sets = list(m["sets"].values())
    assert len(sets) == 1, "both jobs must reuse ONE geometry instance"
    assert sets[0]["epoch"] == 2
    assert sets[0]["state"] == "free"


def test_overlapping_jobs_two_geometries_e2e(tmp_path):
    """ISSUE 14 acceptance: two OVERLAPPING jobs of different
    geometries (np2 + np3) warm-attach concurrently from one daemon
    manifest — both run collectives to completion, each on its own
    set instance."""
    d = str(tmp_path / "dd")
    prog = os.path.join(REPO, "tests", "progs", "lazywire_prog.py")
    env = {"MV2T_DAEMON": "1", "MV2T_DAEMON_DIR": d,
           "MV2T_DAEMON_SPAWN": "0"}
    results = {}

    def job(np_):
        results[np_] = _run_job(env, [sys.executable, prog, "flat"],
                                np_=np_)

    ts = [threading.Thread(target=job, args=(n,)) for n in (2, 3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for np_, r in results.items():
        assert r.returncode == 0, \
            f"np{np_}: stdout={r.stdout}\nstderr={r.stderr}"
        assert "No Errors" in r.stdout
    with open(os.path.join(d, "manifest.json")) as f:
        m = json.load(f)
    assert m["version"] == daemon.MANIFEST_VERSION
    geos = {s["geokey"] for s in m["sets"].values()}
    assert len(geos) == 2, \
        f"expected two geometry sets in one manifest: {m['sets']}"
    assert all(s["state"] == "free" and s["epoch"] >= 1
               for s in m["sets"].values())


def test_daemon_off_is_default_path(tmp_path):
    """MV2T_DAEMON unset: no daemon dir is created or touched."""
    d = str(tmp_path / "dd")
    prog = os.path.join(REPO, "tests", "progs", "lazywire_prog.py")
    r = _run_job({"MV2T_DAEMON_DIR": d}, [sys.executable, prog, "flat"])
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert not os.path.exists(d)


def test_churn_smoke(tmp_path):
    """Tier-1 churn-bench smoke: a few Init/Finalize cycles complete
    through the launcher with the daemon on and off, and report a
    positive cycles/s (the full measurement lives in the BENCH_CHURN
    artifact)."""
    from mvapich2_tpu.bench.churn import churn_rate
    prog = os.path.join(REPO, "tests", "progs", "churn_cycle_prog.py")
    env = {"MV2T_DAEMON_DIR": str(tmp_path / "dd"),
           "MV2T_DAEMON_SPAWN": "0", "JAX_PLATFORMS": "cpu"}
    for dm in (0, 1):
        r = churn_rate([sys.executable, prog], np_=2, cycles=2,
                       daemon=dm, env_extra=env, timeout=240)
        assert r["cps"] > 0 and r["cycles"] == 2, r


def test_churn_concurrent_smoke(tmp_path):
    """The many-jobs-in-flight scenario stays wired: 2 jobs of 2
    geometries overlapping against one daemon dir, cps and the p99
    attach latency reported."""
    from mvapich2_tpu.bench.churn import churn_concurrent
    prog = os.path.join(REPO, "tests", "progs", "churn_cycle_prog.py")
    env = {"MV2T_DAEMON_DIR": str(tmp_path / "dd"),
           "MV2T_DAEMON_SPAWN": "0", "JAX_PLATFORMS": "cpu"}
    r = churn_concurrent([sys.executable, prog], geometries=(2, 3),
                         jobs=2, inflight=2, env_extra=env,
                         timeout=240)
    assert r["cps"] > 0 and r["p99_s"] >= r["p50_s"] > 0, r


@pytest.mark.chaos
def test_overlapping_jobs_full_matrix(tmp_path):
    """Chaos lane: 6 overlapping jobs over np{2,3} against one daemon
    under a tight quota — admission queues, nobody fails, every set
    ends free."""
    from mvapich2_tpu.bench.churn import churn_concurrent
    prog = os.path.join(REPO, "tests", "progs", "churn_cycle_prog.py")
    d = str(tmp_path / "dd")
    env = {"MV2T_DAEMON_DIR": d, "MV2T_DAEMON_SPAWN": "0",
           "MV2T_DAEMON_QUOTA": "2", "JAX_PLATFORMS": "cpu"}
    r = churn_concurrent([sys.executable, prog], geometries=(2, 3),
                         jobs=6, inflight=3, env_extra=env,
                         timeout=600)
    assert r["cps"] > 0, r
    with open(os.path.join(d, "manifest.json")) as f:
        m = json.load(f)
    assert all(s["state"] == "free" for s in m["sets"].values())
    assert m["queue"] == []

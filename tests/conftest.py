"""Test harness configuration.

Multi-chip paths are tested on a virtual 8-device CPU mesh
(xla_force_host_platform_device_count) — the analog of the reference suite
running N ranks on localhost (SURVEY §4: "no fake backend; N processes on
localhost"). Env must be set before jax is first imported.
"""

import os

# Force CPU for the test suite even when the session env points at a real
# accelerator (e.g. JAX_PLATFORMS=axon, whose sitecustomize overrides the
# env var — jax.config must be updated post-import): tests need the virtual
# 8-device mesh. Set MV2T_TEST_ON_TPU=1 to run against real hardware.
if not os.environ.get("MV2T_TEST_ON_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
# keep CI deterministic and quiet
os.environ.setdefault("JAX_ENABLE_X64", "0")

if not os.environ.get("MV2T_TEST_ON_TPU"):
    try:
        import jax
        jax.config.update("jax_platforms", "cpu")
    except ImportError:
        pass

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy multi-process / C-compile / large-model tests — "
        "skipped by default so the suite finishes in minutes on a "
        "1-core host; run everything with MV2T_TEST_FULL=1")
    config.addinivalue_line(
        "markers",
        "lint: static-analysis gate (bin/mv2tlint --strict) and the "
        "runtime lock-order detector smoke — tier-1 by default; run "
        "only these with -m lint")
    config.addinivalue_line(
        "markers",
        "chaos: full fault-injection matrix (site x kind chaos sweeps, "
        "mid-collective kills, churn) — a small seeded subset runs in "
        "tier-1 unmarked; run the full matrix with -m chaos or "
        "bin/runtests --chaos (or MV2T_TEST_FULL=1)")
    config.addinivalue_line(
        "markers",
        "modelcheck: full-depth shm-protocol model exploration (np=4 "
        "seqlock waves, long-horizon lease) — a small-bound subset "
        "runs in tier-1 unmarked; run the full depth with "
        "-m modelcheck (or MV2T_TEST_FULL=1)")


def pytest_collection_modifyitems(config, items):
    if os.environ.get("MV2T_TEST_FULL"):
        return
    markexpr = config.getoption("-m", default="") or ""
    skip = pytest.mark.skip(reason="slow lane: set MV2T_TEST_FULL=1")
    skip_chaos = pytest.mark.skip(
        reason="chaos lane: run with -m chaos (or MV2T_TEST_FULL=1)")
    skip_model = pytest.mark.skip(
        reason="modelcheck lane: run with -m modelcheck (or "
               "MV2T_TEST_FULL=1)")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
        if "chaos" in item.keywords and "chaos" not in markexpr:
            item.add_marker(skip_chaos)
        if "modelcheck" in item.keywords and "modelcheck" not in markexpr:
            item.add_marker(skip_model)

"""bin/mpistat — live attach-not-construct monitoring (ISSUE 10
tentpole). The monitor attaches READ-ONLY to a running (untraced) job's
shm segments and reports per-rank fast-path pvar snapshots, lease ages,
ring depths, and flat-region states — without perturbing the job (it
must still finish with "No Errors"). Plus discovery/format units that
need no live job."""

import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MPISTAT = os.path.join(REPO, "bin", "mpistat")
TARGET = os.path.join(REPO, "tests", "progs", "mpistat_target_prog.py")


def test_mpistat_attaches_to_live_untraced_job():
    env = dict(os.environ)
    env["MV2T_TEST_STAT_SECONDS"] = "8"
    env.pop("MV2T_TRACE", None)      # the job runs UNTRACED
    env.pop("MV2T_NTRACE", None)
    job = subprocess.Popen(
        [sys.executable, "-m", "mvapich2_tpu.run", "-np", "2",
         sys.executable, TARGET],
        cwd=REPO, env=env, stdout=subprocess.PIPE, text=True)
    try:
        # rank 0 prints its segment stem first thing after Init
        seg = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            line = job.stdout.readline()
            if line.startswith("SEG "):
                seg = line.split()[1]
                break
        assert seg, "target job never printed its segment stem"
        time.sleep(2.0)              # let some collectives run
        r = subprocess.run([sys.executable, MPISTAT, "--seg", seg],
                           capture_output=True, text=True, timeout=60)
        assert r.returncode == 0, r.stdout + r.stderr
        out = r.stdout
        assert "2 local ranks" in out
        assert "rank 0:" in out and "rank 1:" in out
        assert "lease" in out
        # the job is mid-allreduce-loop: the fp counter mirror shows
        # flat-tier activity on an UNTRACED job
        assert "fp_coll_flat=" in out
        assert "flat region" in out
        # ...and the attach did not perturb it: clean completion
        rest = job.stdout.read()
        assert job.wait(timeout=120) == 0
        assert "No Errors" in rest
    finally:
        if job.poll() is None:
            job.kill()


def test_mpistat_no_segments_message(tmp_path):
    """With no discoverable job the CLI reports and exits 1 (scan is
    pinned to an empty stem so a concurrently running suite job can't
    race the assertion)."""
    r = subprocess.run(
        [sys.executable, MPISTAT, "--seg", str(tmp_path / "nope")],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 1
    assert "cannot read" in r.stdout or "no live" in r.stdout


def test_flags_len_inversion():
    """mpistat derives n_local from the flags-file size alone; the
    inversion must agree with runtime/boot.py flags_len for every
    plausible n."""
    from mvapich2_tpu.runtime.boot import flags_len
    from mvapich2_tpu.trace.mpistat import _n_local_from_flags
    for n in (1, 2, 3, 4, 7, 8, 16, 64, 256):
        assert _n_local_from_flags(flags_len(n)) == n
    assert _n_local_from_flags(flags_len(4) + 1) is None


def test_snapshot_reads_untraced_segment_offline(tmp_path):
    """snapshot() decodes a synthetic segment set (flags + ring) —
    layout agreement with the writers, no job needed."""
    import struct

    from mvapich2_tpu.runtime.boot import flags_len
    from mvapich2_tpu.trace.mpistat import format_snapshot, snapshot
    n = 2
    stem = str(tmp_path / "mv2t-shm-test")
    ring_bytes = 1 << 16
    with open(stem, "wb") as f:
        f.write(b"\0" * (n * n * ring_bytes))
    # a pending message in ring (0 -> 1): head=0, tail=64
    with open(stem, "r+b") as f:
        f.seek((0 * n + 1) * ring_bytes)
        f.write(struct.pack("<QQ", 0, 64))
    buf = bytearray(flags_len(n))
    lease_off = 8
    now_us = int(time.clock_gettime(time.CLOCK_MONOTONIC) * 1e6)
    struct.pack_into("<Q", buf, lease_off, now_us - 1_500_000)
    struct.pack_into("<Q", buf, lease_off + 8,
                     0xFFFFFFFFFFFFFFFF)          # rank 1 departed
    fpc_off = lease_off + 16
    struct.pack_into("<Q", buf, fpc_off + 8 * 6, 42)   # fp_coll_flat
    buf[0] = 1                                    # rank 0 sleeping
    with open(stem + ".flags", "wb") as f:
        f.write(bytes(buf))
    snap = snapshot(stem)
    assert snap["n_local"] == 2
    assert snap["ranks"][0]["sleeping"] is True
    assert snap["ranks"][0]["lease_age"].endswith("s")
    assert snap["ranks"][1]["lease_age"] == "departed"
    assert snap["ranks"][0]["fp"]["fp_coll_flat"] == 42
    assert snap["ring_depths"] == {"0->1": 64}
    text = format_snapshot(snap)
    assert "sleeping" in text and "departed" in text \
        and "fp_coll_flat=42" in text and "0->1:64B" in text

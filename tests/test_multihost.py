"""Multi-node launch: hostfile grammar, rank mapping, mpispawn agent tree.

The reference's backbone is mpirun_rsh starting one mpispawn per node
(mpispawn_tree.c); here the tree is exercised with emulated nodes on
localhost — unresolvable hostnames run the agent as a local subprocess
with the node identity carried in the bootstrap env, so node_ids, the shm
intra-node channel, and the two-level inter-leader TCP phase all follow
the hostfile placement.
"""

import os
import subprocess
import sys

import pytest

from mvapich2_tpu.runtime.hostfile import (HostSpec, map_ranks,
                                           parse_hostfile_text)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# hostfile grammar + mapping
# ---------------------------------------------------------------------------

def test_parse_forms():
    hosts = parse_hostfile_text(
        "# cluster\n"
        "nodeA\n"
        "nodeB:4\n"
        "nodeC slots=8\n"
        "\n"
        "nodeA:3   # accumulate\n")
    assert hosts == [HostSpec("nodeA", 4), HostSpec("nodeB", 4),
                     HostSpec("nodeC", 8)]


def test_parse_errors():
    with pytest.raises(ValueError):
        parse_hostfile_text("")
    with pytest.raises(ValueError):
        parse_hostfile_text("nodeA:0\n")
    with pytest.raises(ValueError):
        parse_hostfile_text("nodeA gpus=2\n")


def test_map_block_and_cyclic():
    hosts = [HostSpec("a", 2), HostSpec("b", 2)]
    assert map_ranks(hosts, 4, "block") == [
        (0, "a"), (1, "a"), (2, "b"), (3, "b")]
    assert map_ranks(hosts, 4, "cyclic") == [
        (0, "a"), (1, "b"), (2, "a"), (3, "b")]
    # oversubscription wraps
    assert [h for _, h in map_ranks(hosts, 6, "block")] == [
        "a", "a", "b", "b", "a", "a"]


# ---------------------------------------------------------------------------
# agent-tree end-to-end
# ---------------------------------------------------------------------------

def _write_hostfile(tmp_path, text):
    p = tmp_path / "hosts"
    p.write_text(text)
    return str(p)


@pytest.mark.slow
def test_tree_two_nodes_placement(tmp_path):
    """8 ranks over 2 emulated nodes: every rank must see 2 nodes, with
    its node peers matching the hostfile block mapping."""
    prog = tmp_path / "prog.py"
    prog.write_text(
        "import sys; sys.path.insert(0, %r)\n"
        "import numpy as np\n"
        "from mvapich2_tpu import mpi\n"
        "mpi.Init()\n"
        "c = mpi.COMM_WORLD\n"
        "u = c.u\n"
        "assert u.num_nodes() == 2, u.node_ids\n"
        "expect_node = 0 if c.rank < 4 else 1\n"
        "assert u.node_ids[c.rank] == expect_node, (c.rank, u.node_ids)\n"
        "out = c.allreduce(np.full(4096, float(c.rank), np.float32))\n"
        "assert out[0] == sum(range(c.size))\n"
        "shm = c.split_type_shared()\n"
        "assert shm.size == 4\n"
        "if c.rank == 0: print('No Errors')\n"
        "mpi.Finalize()\n" % REPO)
    hf = _write_hostfile(tmp_path, "nodeA:4\nnodeB:4\n")
    r = subprocess.run(
        [sys.executable, "-m", "mvapich2_tpu.run", "-np", "8",
         "--hostfile", hf, "--timeout", "120", sys.executable, str(prog)],
        cwd=REPO, capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout


@pytest.mark.slow
def test_tree_cyclic_mapping(tmp_path):
    prog = tmp_path / "prog.py"
    prog.write_text(
        "import sys; sys.path.insert(0, %r)\n"
        "from mvapich2_tpu import mpi\n"
        "mpi.Init()\n"
        "c = mpi.COMM_WORLD\n"
        "u = c.u\n"
        "assert u.node_ids[c.rank] == c.rank %% 2, u.node_ids\n"
        "c.barrier()\n"
        "if c.rank == 0: print('No Errors')\n"
        "mpi.Finalize()\n" % REPO)
    hf = _write_hostfile(tmp_path, "nodeA:2\nnodeB:2\n")
    r = subprocess.run(
        [sys.executable, "-m", "mvapich2_tpu.run", "-np", "4",
         "--hostfile", hf, "--map", "cyclic", "--timeout", "90",
         sys.executable, str(prog)],
        cwd=REPO, capture_output=True, text=True, timeout=150)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout


@pytest.mark.slow
def test_tree_ft_failure_events_cross_agents(tmp_path):
    """FT mode through the agent tree: a rank killed on one emulated node
    becomes a global failure event (atomic cross-agent sequencing) and
    survivors on both nodes ack + shrink + finish."""
    prog = os.path.join(REPO, "tests", "progs", "ft_shrink_prog.py")
    hf = _write_hostfile(tmp_path, "nodeA:2\nnodeB:2\n")
    r = subprocess.run(
        [sys.executable, "-m", "mvapich2_tpu.run", "-np", "4", "--ft",
         "--hostfile", hf, "--timeout", "120", sys.executable, prog],
        cwd=REPO, capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "No Errors" in r.stdout


@pytest.mark.slow
def test_tree_failing_rank_kills_job(tmp_path):
    prog = os.path.join(REPO, "tests", "progs", "die_prog.py")
    hf = _write_hostfile(tmp_path, "nodeA:2\nnodeB:2\n")
    r = subprocess.run(
        [sys.executable, "-m", "mvapich2_tpu.run", "-np", "4",
         "--hostfile", hf, "--timeout", "90", sys.executable, prog],
        cwd=REPO, capture_output=True, text=True, timeout=150)
    assert r.returncode != 0


@pytest.mark.slow
def test_abort_kills_tree_job(tmp_path):
    """MPI_Abort tears down a multi-node (agent-tree) job too: the
    launcher watches the same KVS abort event on the tree path and
    propagates the errorcode."""
    import subprocess
    import sys
    hf = tmp_path / "hosts"
    hf.write_text("emuA slots=2\nemuB slots=2\n")
    prog = os.path.join(REPO, "tests", "progs", "abort_prog.py")
    r = subprocess.run(
        [sys.executable, "-m", "mvapich2_tpu.run", "-np", "4",
         "--hostfile", str(hf), sys.executable, prog],
        cwd=REPO, capture_output=True, text=True, timeout=90)
    assert r.returncode == 7, (r.returncode, r.stderr[-300:])
    assert "MPI_Abort(7)" in r.stderr

"""Persistent shm arena unit + integration tests (transport/arena.py).

Covers the ISSUE-3 satellite checklist: slot exhaustion falls back to
the scratch-file path (never deadlocks), handle leaks are detected at
Finalize/close, alloc/free is thread-safe, and a dead leader's segment
is swept by the next bootstrap on the node.
"""

import os
import subprocess
import sys
import tempfile
import threading
import uuid

import numpy as np
import pytest

from mvapich2_tpu.transport.arena import ShmArena, cma_read


def _dir():
    return "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()


def _mk(n_local=2, my_index=0, part_bytes=1 << 20, create=True, path=None):
    path = path or os.path.join(
        _dir(), f"mv2t-arena-{os.getpid()}-{uuid.uuid4().hex[:8]}")
    return ShmArena(path, n_local, my_index, part_bytes, create=create), path


def test_alloc_free_roundtrip():
    ar, path = _mk()
    try:
        h = ar.alloc(1000)
        assert h is not None
        assert h.cls >= 1000 and h.off >= 0
        ar.view(h.off, 1000)[:] = np.arange(1000, dtype=np.uint8) % 251
        got = ar.view(h.off, 1000)
        assert np.array_equal(got, np.arange(1000, dtype=np.uint8) % 251)
        assert ar.outstanding == 1
        ar.free(h)
        assert ar.outstanding == 0
        # freed block is reused (registration-cache discipline)
        h2 = ar.alloc(1000)
        assert h2.off == h.off
        ar.free(h2)
    finally:
        ar.close(unlink=True)


def test_size_classes_and_reuse():
    ar, path = _mk(part_bytes=4 << 20)
    try:
        small = ar.alloc(1)
        assert small.cls == ShmArena.MIN_CLASS
        big = ar.alloc(ShmArena.MIN_CLASS + 1)
        assert big.cls == 2 * ShmArena.MIN_CLASS
        assert ar.bytes_in_use == small.cls + big.cls
        ar.free(small)
        ar.free(big)
        assert ar.bytes_in_use == 0
    finally:
        ar.close(unlink=True)


def test_exhaustion_returns_none_not_deadlock():
    """A full partition returns None (caller falls back to the scratch
    file) — alloc never blocks waiting for a free."""
    ar, path = _mk(part_bytes=256 * 1024)
    try:
        held = []
        while True:
            h = ar.alloc(ShmArena.MIN_CLASS)
            if h is None:
                break
            held.append(h)
        assert len(held) == (256 * 1024) // ShmArena.MIN_CLASS
        # oversize-vs-partition is also a clean None
        assert ar.alloc(1 << 30) is None
        ar.free(held.pop())
        assert ar.alloc(ShmArena.MIN_CLASS) is not None  # reuse after free
    finally:
        ar.close(unlink=True)


def test_partition_isolation():
    """Ranks allocate only from their own partition but read anywhere."""
    ar0, path = _mk(n_local=2, my_index=0)
    ar1 = ShmArena(path, 2, 1, ar0.part_bytes, create=False)
    try:
        h0 = ar0.alloc(4096)
        h1 = ar1.alloc(4096)
        lo0, hi0 = ar0._part_lo, ar0._part_hi
        lo1, hi1 = ar1._part_lo, ar1._part_hi
        assert lo0 <= h0.off < hi0
        assert lo1 <= h1.off < hi1
        assert hi0 <= lo1            # disjoint
        ar0.view(h0.off, 4)[:] = (1, 2, 3, 4)
        assert list(ar1.view(h0.off, 4)) == [1, 2, 3, 4]  # cross-read
        ar0.free(h0)
        ar1.free(h1)
    finally:
        ar1.close()
        ar0.close(unlink=True)


def test_concurrent_alloc_free_two_threads():
    """MPI-IO workers + THREAD_MULTIPLE hit the allocator concurrently."""
    ar, path = _mk(part_bytes=8 << 20)
    errs = []

    def body():
        try:
            for _ in range(300):
                hs = [ar.alloc(ShmArena.MIN_CLASS) for _ in range(4)]
                for h in hs:
                    if h is not None:
                        ar.free(h)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=body) for _ in range(2)]
    try:
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert not errs, errs
        assert ar.outstanding == 0
        assert ar.bytes_in_use == 0
    finally:
        ar.close(unlink=True)


def test_spill_consumed_counters():
    ar0, path = _mk(n_local=2, my_index=0)
    ar1 = ShmArena(path, 2, 1, ar0.part_bytes, create=False)
    try:
        assert ar0.spill_consumed(0, 1) == 0
        ar1.bump_spill(0, 1)
        ar1.bump_spill(0, 1)
        assert ar0.spill_consumed(0, 1) == 2
        assert ar0.spill_consumed(1, 0) == 0
    finally:
        ar1.close()
        ar0.close(unlink=True)


def test_sweep_stale_segment():
    """Crash cleanup: a segment whose creator pid is gone is unlinked by
    the next leader's sweep; live-pid segments survive."""
    d = tempfile.mkdtemp(prefix="arena-sweep-")
    # a pid that cannot exist (> pid_max)
    dead = os.path.join(d, "mv2t-arena-99999999-deadbeef")
    open(dead, "wb").close()
    # ring stems + dotted siblings of a crashed leader sweep too (the
    # sparse .fcoll/.fcoll2 segments' touched pages are real tmpfs)
    dead_ring = os.path.join(d, "mv2t-shm-99999999-deadbeef")
    dead_f2 = dead_ring + ".fcoll2"
    open(dead_ring, "wb").close()
    open(dead_f2, "wb").close()
    live = os.path.join(d, f"mv2t-arena-{os.getpid()}-cafecafe")
    open(live, "wb").close()
    other = os.path.join(d, "unrelated-file")
    open(other, "wb").close()
    n = ShmArena.sweep_stale(d)
    assert n == 3
    assert not os.path.exists(dead)
    assert not os.path.exists(dead_ring)
    assert not os.path.exists(dead_f2)
    assert os.path.exists(live)
    assert os.path.exists(other)
    for p in (live, other):
        os.unlink(p)
    os.rmdir(d)


def test_cma_read_self():
    """process_vm_readv against our own pid (what the in-process fabric
    and the unanimous-CMA sectioned exchange rely on)."""
    src = np.arange(1 << 16, dtype=np.uint8)
    out = np.empty(1 << 16, dtype=np.uint8)
    try:
        cma_read(os.getpid(), src.ctypes.data, out, chunk=4096)
    except OSError:
        pytest.skip("process_vm_readv unavailable in this sandbox")
    assert np.array_equal(out, src)


def test_channel_close_detects_handle_leak():
    """ShmChannel.close() warns when exposures were never released —
    the Finalize leak check. Drive it through a real 2-rank process run
    where rank 0 exposes a buffer and exits without its FIN."""
    prog = os.path.join(os.path.dirname(__file__), "progs",
                        "arena_leak_prog.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", MV2T_USE_CMA="0")
    r = subprocess.run([sys.executable, "-m", "mvapich2_tpu.run", "-np",
                       "2", sys.executable, prog], cwd=repo,
                       capture_output=True, text=True, timeout=300,
                       env=env)
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    assert "LEAK-DETECTED" in r.stdout, f"{r.stdout}\n{r.stderr}"


def test_rendezvous_arena_exhaustion_fallback_process_mode():
    """With a partition too small for even one chunk pair, every large
    send must fall back to the scratch-file path and still deliver
    (the cma_rndv integrity prog, CMA off, 64 KiB arena)."""
    prog = os.path.join(os.path.dirname(__file__), "progs",
                        "cma_rndv_prog.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", MV2T_USE_CMA="0",
               MV2T_ARENA_BYTES="65536")
    r = subprocess.run([sys.executable, "-m", "mvapich2_tpu.run", "-np",
                       "2", sys.executable, prog], cwd=repo,
                       capture_output=True, text=True, timeout=300,
                       env=env)
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    assert "No Errors" in r.stdout
